"""paddle.reader — generator-composition decorators of the fluid era.

Reference analogue: /root/reference/python/paddle/reader/decorator.py
(cache:51, map_readers:91, shuffle:133, chain:182, compose:247,
buffered:307, firstn:366, xmap_readers:411, multiprocess_reader:504).

A "reader" is a zero-arg callable returning an iterable of samples.
These combinators compose readers; they are pure host-side Python and
feed `paddle.batch` → the TPU input pipeline (io/DataLoader does the
device staging).  xmap_readers/buffered use daemon threads + queues —
the same overlap the reference gets, without its process fork
machinery (multiprocess_reader degrades to threads here: the samples
land in host RAM either way, and the TPU feed is the bottleneck).
"""
import itertools
import queue as _queue
import random as _random
import threading

__all__ = ['cache', 'map_readers', 'buffered', 'compose', 'chain',
           'shuffle', 'firstn', 'xmap_readers', 'multiprocess_reader']


class ComposeNotAligned(ValueError):
    pass


def _put_or_stop(q, item, stop, poll_s=0.1):
    """put() that gives up when `stop` is set — worker threads must not
    park forever on a bounded queue after the consumer abandons the
    generator.  Returns False when stopped."""
    while not stop.is_set():
        try:
            q.put(item, timeout=poll_s)
            return True
        except _queue.Full:
            continue
    return False


def cache(reader):
    """Materialize `reader`'s samples in memory on first COMPLETE
    iteration; later passes replay the cached list (reference
    decorator.py:51).  The cache is built in a local list and only
    published once the pass finishes, so an abandoned partial pass
    (firstn, zip with a shorter reader) cannot corrupt it."""
    state = {'data': None}

    def cached_reader():
        if state['data'] is not None:
            yield from state['data']
            return
        fresh = []
        for item in reader():
            fresh.append(item)
            yield item
        state['data'] = fresh

    return cached_reader


def map_readers(func, *readers):
    """Zip N readers and map `func` over the per-reader samples
    (reference decorator.py:91)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: fill a buf_size window, shuffle, drain
    (reference decorator.py:133)."""

    def shuffled_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled_reader


def chain(*readers):
    """Concatenate readers back to back (reference decorator.py:182)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, **kwargs):
    """Zip readers into combined samples: sample tuples are flattened
    into one tuple per step (reference decorator.py:247).  With
    check_alignment=True (default) raises ComposeNotAligned when the
    readers end at different lengths."""
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(map(make_tuple, outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        'outputs of readers are not aligned')
                yield sum(map(make_tuple, outputs), ())

    return reader


def buffered(reader, size):
    """Producer thread fills a bounded queue of `size` samples; the
    consumer overlaps with production (reference decorator.py:307)."""

    class _End:
        pass

    def buffered_reader():
        q = _queue.Queue(maxsize=size)
        stop = threading.Event()

        def produce():
            try:
                for item in reader():
                    if not _put_or_stop(q, item, stop):
                        return
                _put_or_stop(q, _End, stop)
            except BaseException as e:
                # surface producer failures in the consumer — a
                # swallowed error would look like a short epoch
                _put_or_stop(q, e, stop)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            import time as _time
            from ..telemetry import active as _tel_active
            gauge = _tel_active()
            while True:
                if gauge:
                    # host-wait gauge: time blocked on the producer
                    # (same counter family as io.DataLoader's — the
                    # run report's host-wait split reads both)
                    _t0 = _time.perf_counter()
                    item = q.get()
                    from .. import telemetry
                    telemetry.add('io.reader.wait_s',
                                  _time.perf_counter() - _t0)
                else:
                    item = q.get()
                if item is _End:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # consumer abandoned early (firstn/zip/early-stop): release
            # the producer instead of leaving it parked on a full queue
            stop.set()
            # bounded join — the producer's put-poll re-checks `stop`
            # every 0.1s; the timeout only guards a source reader
            # wedged mid-next()
            t.join(timeout=2.0)

    return buffered_reader


def firstn(reader, n):
    """Limit to the first n samples (reference decorator.py:366)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Map `mapper` over samples with `process_num` worker threads and a
    bounded queue (reference decorator.py:411 — processes there, threads
    here; see module docstring).  order=True preserves input order."""

    end_token = object()

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        stop = threading.Event()

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    if not _put_or_stop(in_q, (i, sample), stop):
                        return
            except BaseException as e:
                _put_or_stop(out_q, e, stop)
            finally:
                # workers must always see their end tokens or they (and
                # then the consumer) would block forever
                for _ in range(process_num):
                    if not _put_or_stop(in_q, end_token, stop):
                        return

        def work():
            while not stop.is_set():
                try:
                    item = in_q.get(timeout=0.1)
                except _queue.Empty:
                    continue
                if item is end_token:
                    _put_or_stop(out_q, end_token, stop)
                    return
                i, sample = item
                try:
                    _put_or_stop(out_q, (i, mapper(sample)), stop)
                except BaseException as e:
                    _put_or_stop(out_q, e, stop)
                    _put_or_stop(out_q, end_token, stop)
                    return

        threads = [threading.Thread(target=feed, daemon=True)]
        threads.extend(threading.Thread(target=work, daemon=True)
                       for _ in range(process_num))
        for t in threads:
            t.start()

        finished = 0
        try:
            if not order:
                while finished < process_num:
                    item = out_q.get()
                    if item is end_token:
                        finished += 1
                    elif isinstance(item, BaseException):
                        raise item
                    else:
                        yield item[1]
            else:
                pending, next_i = {}, 0
                while finished < process_num or pending:
                    if next_i in pending:
                        yield pending.pop(next_i)
                        next_i += 1
                        continue
                    if finished == process_num:
                        # all workers done; next index never arrived
                        break
                    item = out_q.get()
                    if item is end_token:
                        finished += 1
                    elif isinstance(item, BaseException):
                        raise item
                    else:
                        pending[item[0]] = item[1]
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        finally:
            stop.set()
            # feeder and workers all poll `stop` on their queue ops, so
            # they exit within one 0.1s tick — bounded join, no leak
            for t in threads:
                t.join(timeout=2.0)

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave N readers concurrently (reference decorator.py:504).
    Thread-backed: each reader drains into a shared queue from its own
    thread; samples arrive in completion order."""
    if len(readers) < 1:
        raise ValueError('multiprocess_reader needs at least one reader')

    end_token = object()

    def mp_reader():
        q = _queue.Queue(queue_size)
        stop = threading.Event()

        def drain(r):
            try:
                for sample in r():
                    if not _put_or_stop(q, (None, sample), stop):
                        return
            except BaseException as e:
                _put_or_stop(q, (e, None), stop)
            finally:
                _put_or_stop(q, end_token, stop)

        threads = [threading.Thread(target=drain, args=(r,), daemon=True)
                   for r in readers]
        for t in threads:
            t.start()
        finished = 0
        try:
            while finished < len(readers):
                item = q.get()
                if item is end_token:
                    finished += 1
                elif item[0] is not None:
                    raise item[0]
                else:
                    yield item[1]
        finally:
            stop.set()
            # drainers poll `stop` on put, so this completes within one
            # 0.1s tick per thread — bounded join, no orphan threads
            for t in threads:
                t.join(timeout=2.0)

    return mp_reader
