"""paddle_tpu.amp — automatic mixed precision.

Reference analogue: /root/reference/python/paddle/amp/auto_cast.py and
grad_scaler.py (which wrap the C++ dygraph tracer's AMP lists, see
paddle/fluid/imperative/amp_auto_cast.cc).  TPU-native: the preferred
low-precision dtype is bfloat16 — same exponent range as float32, so no
loss scaling is *needed*; GradScaler is kept fully operative anyway for
float16 use and API parity.  Casting happens at the single eager
dispatch choke point (core/dispatch.set_amp_hook) instead of per-op C++
wrappers, and the compiled path (paddle_tpu.jit) applies the same policy
during tracing so the casts land inside the XLA module where they fuse
into the matmuls for free.
"""
import contextlib

import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor
from ..core.dtype import convert_dtype

__all__ = ['auto_cast', 'amp_guard', 'decorate', 'amp_decorate',
           'GradScaler', 'WHITE_LIST', 'BLACK_LIST', 'audit']

# Ops whose FLOPs dominate and which the MXU runs natively in bf16.
# Mirrors the reference's white list {conv2d, matmul, mul} plus our op
# names for the same computations.
WHITE_LIST = frozenset({
    'matmul', 'bmm', 'mv', 'dot', 'mul', 'linear', 'conv1d', 'conv2d',
    'conv3d', 'conv2d_transpose', 'conv1d_transpose', 'conv3d_transpose',
    'einsum', 'addmm', 'fused_linear_gelu', 'flash_attention',
})

# Numerically-sensitive ops kept in float32 (reference black list:
# exp/log/softmax/cross_entropy/... — reductions and transcendentals).
BLACK_LIST = frozenset({
    'exp', 'expm1', 'log', 'log2', 'log10', 'log1p', 'pow', 'square',
    'sqrt', 'rsqrt', 'reciprocal', 'softmax', 'log_softmax',
    'cross_entropy', 'softmax_with_cross_entropy', 'nll_loss',
    'binary_cross_entropy', 'bce_with_logits',
    'kl_div', 'cosh', 'sinh', 'tan', 'mean', 'sum', 'norm', 'dist',
    'reduce_mean', 'reduce_sum', 'cumsum', 'logsumexp', 'softplus',
    'erf', 'erfinv', 'lgamma', 'digamma', 'cross_entropy_loss',
    # loss heads compute in f32 even when the step runs under an O1/O2
    # autocast (ParallelTrainer wraps loss_fn in the forward's policy):
    # each dispatches as ONE op, so without this a bf16 forward output
    # would drag the f32 labels down via the gray/O2 rules
    'mse_loss', 'l1_loss', 'square_error_cost', 'smooth_l1_loss',
    'margin_ranking_loss', 'hinge_embedding_loss',
    'cosine_embedding_loss', 'log_loss', 'ctc_loss',
    'sigmoid_focal_loss',
})

# Normalization ops manage their own mixed precision: the functionals in
# nn/functional/norm.py compute statistics with float32 accumulation and
# apply the normalization in the input dtype (folded per-channel
# scale/shift that XLA fuses into the producing conv/matmul epilogue).
# Casting their inputs here — either direction — would only add HBM
# traffic: an f32 upcast doubles the activation bytes saved for backward
# (this was the round-1 ResNet bottleneck: the step was HBM-bound with
# every BN materializing f32 copies), while a bf16 downcast would round
# the f32 scale/shift parameters for no gain.
KEEP_LIST = frozenset({
    'layer_norm', 'batch_norm', 'instance_norm', 'group_norm',
})

_FLOATS = (jnp.float32, jnp.float16, jnp.bfloat16, jnp.float64)


class _AmpState:
    def __init__(self):
        self.enabled = False
        self.level = 'O1'
        self.dtype = jnp.bfloat16
        self.white = WHITE_LIST
        self.black = BLACK_LIST


_state = _AmpState()


def _is_float(v):
    return v.dtype in _FLOATS


def _cast_all(vals, dtype):
    return [v.astype(dtype) if _is_float(v) and v.dtype != dtype else v
            for v in vals]


def _amp_hook(op_name, vals):
    if not _state.enabled:
        return vals
    if (op_name in KEEP_LIST and op_name not in _state.black
            and op_name not in _state.white):  # custom lists still win
        return vals
    if op_name in _state.black:
        return _cast_all(vals, jnp.float32)
    if _state.level == 'O2':
        # pure-low-precision mode: everything not blacklisted runs low
        return _cast_all(vals, _state.dtype)
    if op_name in _state.white:
        return _cast_all(vals, _state.dtype)
    # O1 gray ops: if any input is already low precision, follow it —
    # keeps elementwise chains fused in bf16 between matmuls.
    if any(_is_float(v) and v.dtype == _state.dtype for v in vals):
        return _cast_all(vals, _state.dtype)
    return vals


dispatch.set_amp_hook(_amp_hook)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level='O1', dtype='bfloat16'):
    """Context manager enabling mixed precision (reference:
    python/paddle/amp/auto_cast.py::amp_guard)."""
    if level not in ('O0', 'O1', 'O2'):
        raise ValueError(f"level must be O0/O1/O2, got {level}")
    prev = (_state.enabled, _state.level, _state.dtype, _state.white,
            _state.black)
    _state.enabled = bool(enable) and level != 'O0'
    _state.level = level
    _state.dtype = convert_dtype(dtype) or jnp.bfloat16
    white, black = set(WHITE_LIST), set(BLACK_LIST)
    if custom_white_list:
        white |= set(custom_white_list)
        black -= set(custom_white_list)
    if custom_black_list:
        black |= set(custom_black_list)
        white -= set(custom_black_list)
    _state.white, _state.black = frozenset(white), frozenset(black)
    try:
        yield
    finally:
        (_state.enabled, _state.level, _state.dtype, _state.white,
         _state.black) = prev


amp_guard = auto_cast


def is_amp_enabled():
    return _state.enabled


def amp_state():
    """(enabled, level, dtype) — read by paddle_tpu.jit so compiled
    traces apply the same policy."""
    return _state


def audit():
    """Eager mixed-precision audit (paddle_tpu.analysis.amp_audit):

        with amp.audit() as a, amp.auto_cast():
            model(x)
        print(a.report())   # amp-promotion findings: f32 operands the
                            # hook re-casts every step

    The jaxpr-level twin (f32 creep inside compiled steps) runs via
    analysis.lint / to_static(check=...) / Model.prepare(lint=...)."""
    from ..analysis import amp_audit
    return amp_audit()


def decorate(models, optimizers=None, level='O1', dtype='bfloat16',
             master_weight=None, save_dtype=None):
    """Reference: paddle.amp.decorate.  O2 casts model params to the low
    dtype (master weights stay fp32 inside the optimizer when
    multi_precision is on)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == 'O2':
        target = convert_dtype(dtype) or jnp.bfloat16
        for m in model_list:
            for p in m.parameters():
                if _is_float(p.value):
                    p.value = p.value.astype(target)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


amp_decorate = decorate


class GradScaler:
    """Reference: python/paddle/amp/grad_scaler.py.  Loss-scaling for
    float16; with bfloat16 (TPU default) scaling is a no-op numerically
    but the dynamic-scale state machine still runs for API parity and
    the non-finite-gradient *skip* remains active as a NaN guard."""

    def __init__(self, enable=True, init_loss_scaling=2.**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        self._unscaled = True
        params = optimizer._params
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p._grad is not None:
                g = p._grad * inv
                finite = bool(jnp.isfinite(g).all())
                found = found or not finite
                p._grad = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        # reference signature: scaler.minimize(opt, scaled) after
        # scaled.backward(); scaled_loss itself is unused here.
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        self._unscaled = False
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {'scale': self._scale, 'incr_ratio': self._incr_ratio,
                'decr_ratio': self._decr_ratio,
                'incr_every_n_steps': self._incr_every_n_steps,
                'decr_every_n_nan_or_inf': self._decr_every_n,
                'good_steps': self._good_steps,
                'bad_steps': self._bad_steps,
                'use_dynamic_loss_scaling': self._dynamic}

    def load_state_dict(self, state):
        self._scale = state['scale']
        self._incr_ratio = state['incr_ratio']
        self._decr_ratio = state['decr_ratio']
        self._incr_every_n_steps = state['incr_every_n_steps']
        self._decr_every_n = state['decr_every_n_nan_or_inf']
        self._good_steps = state['good_steps']
        self._bad_steps = state['bad_steps']
        self._dynamic = state['use_dynamic_loss_scaling']
