"""`paddle.callbacks` namespace (reference: python/paddle/callbacks.py,
re-exporting hapi/callbacks.py).  The implementations live in
paddle_tpu/hapi/callbacks.py; this module is the stable public path.
"""
from .hapi.callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, VisualDL, LRScheduler,
    EarlyStopping, ReduceLROnPlateau,
)

__all__ = ['Callback', 'ProgBarLogger', 'ModelCheckpoint', 'VisualDL',
           'LRScheduler', 'EarlyStopping', 'ReduceLROnPlateau']
