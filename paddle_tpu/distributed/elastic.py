"""Trainer supervision: watch, restart, clean up local workers.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/launch_utils.py
(`start_local_trainers`:452 spawns one proc per device and the pod
watch loop polls them; `terminate_local_procs`:308 terminates then
SIGKILLs stragglers) and the elastic restart behaviour of
paddle.distributed.fleet.elastic.

TPU-native: one worker process drives all of a host's chips, so the
supervisor watches ONE child per host (more are supported for API
parity).  A dead or wedged worker is restarted up to `max_restarts`
times with `PADDLE_ELASTIC_RESTART_COUNT` exported, and the training
loop resumes from the last auto-checkpoint
(incubate.checkpoint.auto_checkpoint) — together they give the
kill-a-worker-mid-training recovery the reference's pod watcher
provides.  Wedge detection is a heartbeat FILE (the worker's
auto-checkpoint saves touch it): a stale mtime beyond
`heartbeat_timeout` kills and restarts the worker, mirroring the
reference watchdog's hung-trainer path.
"""
import os
import signal
import subprocess
import sys
import time

__all__ = ['TrainerProc', 'start_local_trainers',
           'terminate_local_procs', 'watch_local_trainers', 'supervise']


class TrainerProc:
    """Reference launch_utils.py TrainerProc: one supervised worker."""

    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.rank = None
        self.local_rank = None
        self.cmd = None
        self.env = None
        self.restarts = 0


def start_local_trainers(cmds, log_dir=None, envs=None):
    """Spawn one TrainerProc per command (reference
    launch_utils.py:452).  `cmds`: list of argv lists."""
    procs = []
    for rank, cmd in enumerate(cmds):
        env = dict(os.environ if envs is None else envs)
        env['PADDLE_TRAINER_ID'] = str(rank)
        env['PADDLE_RANK_IN_NODE'] = str(rank)
        t = TrainerProc()
        t.rank = t.local_rank = rank
        t.cmd = list(cmd)
        t.env = env
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            t.log_fn = open(os.path.join(
                log_dir, f'workerlog.{rank}'), 'ab')
        t.proc = subprocess.Popen(
            cmd, env=env, stdout=t.log_fn or None,
            stderr=subprocess.STDOUT if t.log_fn else None)
        procs.append(t)
    return procs


def terminate_local_procs(procs, grace=3.0):
    """Terminate, wait, then SIGKILL stragglers (reference
    launch_utils.py:308 — same escalation, shorter waits)."""
    for p in procs:
        if p.proc is not None and p.proc.poll() is None:
            p.proc.terminate()
        if p.log_fn:
            try:
                p.log_fn.close()
            except Exception:
                pass
            p.log_fn = None
    deadline = time.time() + grace
    while time.time() < deadline:
        if all(p.proc is None or p.proc.poll() is not None
               for p in procs):
            return
        time.sleep(0.05)
    for p in procs:
        if p.proc is not None and p.proc.poll() is None:
            try:
                os.kill(p.proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
    for p in procs:
        if p.proc is not None:
            try:
                p.proc.wait(timeout=grace)
            except Exception:
                pass


def _restart(t, log_dir=None):
    t.restarts += 1
    env = dict(t.env)
    env['PADDLE_ELASTIC_RESTART_COUNT'] = str(t.restarts)
    t.env = env
    if log_dir and t.log_fn is None:
        t.log_fn = open(os.path.join(
            log_dir, f'workerlog.{t.rank}'), 'ab')
    t.proc = subprocess.Popen(
        t.cmd, env=env, stdout=t.log_fn or None,
        stderr=subprocess.STDOUT if t.log_fn else None)


def watch_local_trainers(procs, max_restarts=3, poll=0.2,
                         heartbeat_file=None, heartbeat_timeout=None,
                         log_dir=None, on_event=None):
    """The pod watch loop: poll workers, restart the dead, kill the
    wedged (stale heartbeat), stop everything when one fails beyond
    `max_restarts`.

    Returns 0 when every worker exited cleanly; the failing worker's
    exit code otherwise.  `on_event(kind, trainer)` (kinds 'exit',
    'restart', 'hang') observes transitions — tests and progress
    loggers hook it.
    """
    if bool(heartbeat_file) != bool(heartbeat_timeout):
        raise ValueError(
            'heartbeat_file and heartbeat_timeout must be set '
            'together — one without the other silently disables hang '
            'detection')
    if heartbeat_file:
        # seed the heartbeat at supervision start: a worker that
        # wedges BEFORE its first checkpoint touch must still trip
        # the stale-mtime detector
        with open(heartbeat_file, 'a'):
            os.utime(heartbeat_file, None)
    try:
        while True:
            alive = False
            for t in procs:
                rc = t.proc.poll()
                if rc is None:
                    alive = True
                    if heartbeat_file and heartbeat_timeout and \
                            os.path.exists(heartbeat_file):
                        age = time.time() - os.path.getmtime(
                            heartbeat_file)
                        if age > heartbeat_timeout:
                            if on_event:
                                on_event('hang', t)
                            t.proc.kill()
                            t.proc.wait()
                            rc = t.proc.returncode
                        else:
                            continue
                    else:
                        continue
                if rc == 0:
                    continue
                # dead worker: restart or give up
                if on_event:
                    on_event('exit', t)
                if t.restarts >= max_restarts:
                    terminate_local_procs(
                        [p for p in procs if p is not t])
                    return rc if rc is not None else 1
                if heartbeat_file:
                    # a fresh heartbeat marks the NEW incarnation live
                    with open(heartbeat_file, 'a'):
                        os.utime(heartbeat_file, None)
                _restart(t, log_dir)
                if on_event:
                    on_event('restart', t)
                alive = True
            if not alive:
                return 0
            time.sleep(poll)
    except KeyboardInterrupt:
        terminate_local_procs(procs)
        raise


def supervise(cmd, max_restarts=3, log_dir=None, heartbeat_file=None,
              heartbeat_timeout=None, on_event=None):
    """Run ONE worker command under supervision (the per-host elastic
    entry the launcher's --elastic flag uses)."""
    procs = start_local_trainers([cmd], log_dir=log_dir)
    return watch_local_trainers(
        procs, max_restarts=max_restarts, log_dir=log_dir,
        heartbeat_file=heartbeat_file,
        heartbeat_timeout=heartbeat_timeout, on_event=on_event)
