"""Trainer supervision: watch, restart, clean up local workers.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/launch_utils.py
(`start_local_trainers`:452 spawns one proc per device and the pod
watch loop polls them; `terminate_local_procs`:308 terminates then
SIGKILLs stragglers) and the elastic restart behaviour of
paddle.distributed.fleet.elastic.

TPU-native: one worker process drives all of a host's chips, so the
supervisor watches ONE child per host (more are supported for API
parity).  A dead or wedged worker is restarted up to `max_restarts`
times with `PADDLE_ELASTIC_RESTART_COUNT` exported, and the training
loop resumes from the last auto-checkpoint
(incubate.checkpoint.auto_checkpoint) — together they give the
kill-a-worker-mid-training recovery the reference's pod watcher
provides.  Wedge detection is a heartbeat FILE (the worker's
auto-checkpoint saves touch it): a stale mtime beyond
`heartbeat_timeout` kills and restarts the worker, mirroring the
reference watchdog's hung-trainer path.
"""
import os
import signal
import subprocess
import sys
import time

from ..resilience import PREEMPTED_EXIT_CODE, GracefulShutdown

__all__ = ['TrainerProc', 'start_local_trainers',
           'terminate_local_procs', 'watch_local_trainers', 'supervise',
           'request_reshape', 'PREEMPTED_EXIT_CODE',
           'DEADLINE_EXIT_CODE']

# returned by watch_local_trainers when its `deadline` expires before
# the workers finish: the supervised run wedged (the timeout(1)
# convention code, so shell drivers read it naturally)
DEADLINE_EXIT_CODE = 124


class TrainerProc:
    """Reference launch_utils.py TrainerProc: one supervised worker."""

    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.rank = None
        self.local_rank = None
        self.cmd = None
        self.env = None
        self.restarts = 0
        self.preemptions = 0
        self.reshapes = 0
        self.spawned_at = 0.0


def start_local_trainers(cmds, log_dir=None, envs=None):
    """Spawn one TrainerProc per command (reference
    launch_utils.py:452).  `cmds`: list of argv lists."""
    procs = []
    for rank, cmd in enumerate(cmds):
        env = dict(os.environ if envs is None else envs)
        env['PADDLE_TRAINER_ID'] = str(rank)
        env['PADDLE_RANK_IN_NODE'] = str(rank)
        # worker and supervisor MUST agree on the preemption exit
        # code, or every clean preemption reads as a crash and burns
        # the restart budget (an explicit `envs` dict would otherwise
        # drop the operator's override)
        env['PADDLE_TPU_PREEMPTED_EXIT_CODE'] = str(PREEMPTED_EXIT_CODE)
        t = TrainerProc()
        t.rank = t.local_rank = rank
        t.cmd = list(cmd)
        t.env = env
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            t.log_fn = open(os.path.join(
                log_dir, f'workerlog.{rank}'), 'ab')
        t.proc = subprocess.Popen(
            cmd, env=env, stdout=t.log_fn or None,
            stderr=subprocess.STDOUT if t.log_fn else None)
        t.spawned_at = time.time()
        procs.append(t)
    return procs


def terminate_local_procs(procs, grace=3.0):
    """Terminate, wait, then SIGKILL stragglers (reference
    launch_utils.py:308 — same escalation, shorter waits)."""
    for p in procs:
        if p.proc is not None and p.proc.poll() is None:
            p.proc.terminate()
        if p.log_fn:
            try:
                p.log_fn.close()
            except Exception:
                pass
            p.log_fn = None
    deadline = time.time() + grace
    while time.time() < deadline:
        if all(p.proc is None or p.proc.poll() is not None
               for p in procs):
            return
        time.sleep(0.05)
    for p in procs:
        if p.proc is not None and p.proc.poll() is None:
            try:
                os.kill(p.proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
    for p in procs:
        if p.proc is not None:
            try:
                p.proc.wait(timeout=grace)
            except Exception:
                pass


def _restart(t, log_dir=None, preempted=False, reshape=False,
             extra_env=None):
    """Relaunch a worker.  A clean preemption (exit code
    PREEMPTED_EXIT_CODE after a graceful final checkpoint) bumps the
    preemption counter, NOT the restart counter — the max_restarts
    budget is a *failure* budget, and a fleet that preempts a job ten
    times must not exhaust it.  A supervisor-initiated RESHAPE bumps
    its own counter for the same reason (plus `extra_env`: the new
    mesh/plan riding into the next incarnation)."""
    if reshape:
        t.reshapes += 1
    elif preempted:
        t.preemptions += 1
    else:
        t.restarts += 1
    env = dict(t.env)
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    env['PADDLE_ELASTIC_RESTART_COUNT'] = str(t.restarts)
    env['PADDLE_ELASTIC_PREEMPT_COUNT'] = str(t.preemptions)
    env['PADDLE_ELASTIC_RESHAPE_COUNT'] = str(t.reshapes)
    env['PADDLE_TPU_PREEMPTED_EXIT_CODE'] = str(PREEMPTED_EXIT_CODE)
    t.env = env
    if log_dir and t.log_fn is None:
        t.log_fn = open(os.path.join(
            log_dir, f'workerlog.{t.rank}'), 'ab')
    t.proc = subprocess.Popen(
        t.cmd, env=env, stdout=t.log_fn or None,
        stderr=subprocess.STDOUT if t.log_fn else None)
    t.spawned_at = time.time()


def _seed_heartbeat(heartbeat_file):
    with open(heartbeat_file, 'a'):
        os.utime(heartbeat_file, None)


def _heartbeat_age(heartbeat_file):
    """Seconds since the worker last proved liveness.  A MISSING file
    counts as infinitely stale: a worker (or operator) that deleted
    the heartbeat mid-run used to silently disable hang detection —
    exactly when detection matters most.  Any OTHER stat error
    (ESTALE/EIO on a flaky shared fs) counts as fresh: one transient
    hiccup must not SIGKILL a healthy worker and burn a restart."""
    try:
        return time.time() - os.path.getmtime(heartbeat_file)
    except FileNotFoundError:
        return float('inf')
    except OSError:
        return 0.0


def request_reshape(workdir, mesh=None, env=None, reason=None):
    """Queue a coordinated reshape restart for the supervision loop
    watching `workdir` (``watch_local_trainers(reshape_dir=...)``):
    every worker is gracefully terminated and relaunched together
    with `env` merged in (how a new mesh/plan reaches the next
    incarnation) — WITHOUT consuming the max_restarts budget or
    tripping the crash backoff, the same posture as a fleet
    preemption.  Returns the request's seq."""
    from ..resilience.supervisor import write_reshape_request
    return write_reshape_request(workdir, mesh=mesh, env=env,
                                 reason=reason)


def _coordinated_reshape(procs, req, log_dir, on_event,
                         heartbeat_file):
    """Gracefully stop EVERY worker and relaunch them together with
    the request's env merged in — one restart for the whole cluster,
    free of the failure budget."""
    terminate_local_procs(procs, grace=30.0)
    extra = dict(req.get('env') or {})
    mesh = req.get('mesh')
    if mesh:
        extra.setdefault('PADDLE_TPU_RESHAPE_MESH', ','.join(
            f'{a}={s}' for a, s in mesh.items()))
    if heartbeat_file:
        _seed_heartbeat(heartbeat_file)
    for t in procs:
        _restart(t, log_dir, reshape=True, extra_env=extra)
        if on_event:
            on_event('reshape', t)
    try:
        from ..telemetry import event as _tevent
        _tevent('reshape_restore', initiator='supervisor',
                seq=req.get('seq'), mesh=mesh,
                reason=req.get('reason'))
    except Exception:
        pass


def watch_local_trainers(procs, max_restarts=3, poll=0.2,
                         heartbeat_file=None, heartbeat_timeout=None,
                         log_dir=None, on_event=None, shutdown=None,
                         min_preempt_uptime=None, restart_backoff=1.0,
                         restart_backoff_max=30.0, deadline=None,
                         reshape_dir=None):
    """The pod watch loop: poll workers, restart the dead, kill the
    wedged (stale or deleted heartbeat), stop everything when one
    fails beyond `max_restarts`.

    Returns 0 when every worker exited cleanly; the failing worker's
    exit code otherwise.  A worker exiting PREEMPTED_EXIT_CODE (its
    GracefulShutdown checkpointed and bowed out) is restarted without
    consuming the max_restarts budget — unless it ran for less than
    `min_preempt_uptime` seconds, which marks a preemption loop (e.g.
    an exit-code env mismatch) and counts as a failure.  When `shutdown` (a
    resilience.GracefulShutdown watching the SUPERVISOR's signals) is
    requested, SIGTERM is forwarded to the workers so they checkpoint,
    and the loop returns PREEMPTED_EXIT_CODE itself — preemption
    propagates cleanly through nested supervision.  `on_event(kind,
    trainer)` (kinds 'exit', 'restart', 'hang', 'preempt', 'backoff',
    'watchdog', 'reshape') observes transitions — tests and progress
    loggers hook it.

    `reshape_dir` arms the supervisor-initiated COORDINATED restart
    path: a ``reshape_request.json`` appearing there (written by
    :func:`request_reshape` / the plan supervisor) with a new seq
    gracefully terminates every worker and relaunches them together
    with the request's env merged in.  Reshapes consume NO
    max_restarts budget and trip NO crash backoff — a planned
    migration is not a failure, exactly like a preemption.

    CRASH restarts (not preemptions) back off exponentially:
    restart k of a worker waits ``min(restart_backoff * 2**(k-1),
    restart_backoff_max)`` seconds before respawning.  A crash-looping
    worker (bad import, poisoned checkpoint) used to burn the whole
    max_restarts budget in milliseconds — with backoff the budget
    spans long enough for a transient cause (NFS blip, node coming
    up) to clear.  Preempted workers still respawn immediately: the
    fleet already imposed that wait.

    `deadline` bounds the WHOLE supervision in wall-clock seconds: a
    cluster that neither completes nor fails within it is torn down
    and the loop returns DEADLINE_EXIT_CODE (124) — chaos soaks use
    this as invariant I7 (complete or die loudly, never wedge a
    reservation).  A worker exiting resilience.watchdog's
    WATCHDOG_EXIT_CODE (a self-detected hang) is restarted as a
    normal FAILURE (it consumes the max_restarts budget — a
    deterministic hang must not restart forever) but is surfaced to
    `on_event` as kind 'watchdog' so supervisors and reports can tell
    a hang from a crash.
    """
    from ..resilience.watchdog import WATCHDOG_EXIT_CODE
    watch_deadline = (time.monotonic() + deadline
                      if deadline is not None else None)
    if min_preempt_uptime is None:
        # default 5s, tunable per-deployment: real workers spend far
        # longer than this importing + restoring before any step, but
        # smoke workers (and tests) may legitimately live for less
        min_preempt_uptime = float(os.environ.get(
            'PADDLE_TPU_MIN_PREEMPT_UPTIME', '5'))
    if bool(heartbeat_file) != bool(heartbeat_timeout):
        raise ValueError(
            'heartbeat_file and heartbeat_timeout must be set '
            'together — one without the other silently disables hang '
            'detection')
    if heartbeat_file:
        # seed the heartbeat at supervision start: a worker that
        # wedges BEFORE its first checkpoint touch must still trip
        # the stale-mtime detector
        _seed_heartbeat(heartbeat_file)
    reshape_seq = 0     # act once per NEW request seq
    try:
        while True:
            if reshape_dir is not None:
                from ..resilience.supervisor import \
                    read_reshape_request
                req = read_reshape_request(reshape_dir)
                if req and int(req.get('seq', 0)) > reshape_seq:
                    reshape_seq = int(req['seq'])
                    _coordinated_reshape(procs, req, log_dir,
                                         on_event, heartbeat_file)
                    continue
            if shutdown is not None and shutdown.requested():
                # host preemption reached the supervisor: pass the
                # SIGTERM down (terminate_local_procs starts with
                # terminate() == SIGTERM, so workers run their own
                # graceful checkpoint within the grace window)
                terminate_local_procs(procs, grace=30.0)
                return PREEMPTED_EXIT_CODE
            if watch_deadline is not None and \
                    time.monotonic() > watch_deadline:
                # the I7 backstop: a wedged cluster is torn down and
                # reported as a deadline breach, never left running
                terminate_local_procs(procs, grace=3.0)
                return DEADLINE_EXIT_CODE
            alive = False
            for t in procs:
                rc = t.proc.poll()
                if rc is None:
                    alive = True
                    if heartbeat_file and heartbeat_timeout:
                        age = _heartbeat_age(heartbeat_file)
                        if age > heartbeat_timeout:
                            if on_event:
                                on_event('hang', t)
                            t.proc.kill()
                            t.proc.wait()
                            rc = t.proc.returncode
                        else:
                            continue
                    else:
                        continue
                if rc == 0:
                    continue
                preempted = rc == PREEMPTED_EXIT_CODE
                if preempted and \
                        time.time() - t.spawned_at < min_preempt_uptime:
                    # a worker that claims preemption within seconds
                    # of spawning is looping (env mismatch on the
                    # exit code, shutdown tripped at startup) — count
                    # it against the FAILURE budget or an unbounded
                    # free-restart storm respawns forever
                    preempted = False
                # dead worker: restart or give up
                if on_event:
                    on_event('preempt' if preempted
                             else 'watchdog' if rc == WATCHDOG_EXIT_CODE
                             else 'exit', t)
                if not preempted and t.restarts >= max_restarts:
                    terminate_local_procs(
                        [p for p in procs if p is not t])
                    return rc if rc is not None else 1
                if not preempted and restart_backoff > 0:
                    delay = min(restart_backoff * (2 ** t.restarts),
                                restart_backoff_max)
                    if on_event:
                        on_event('backoff', t)
                    try:
                        from ..telemetry import event as _tevent
                        _tevent('restart_backoff', rank=t.rank,
                                restarts=t.restarts,
                                delay_s=round(delay, 3))
                    except Exception:
                        pass
                    # chunked: a SIGTERM (fleet preemption) arriving
                    # mid-backoff must still reach the OTHER workers
                    # within the kill-grace window, not wait out a
                    # 30s sleep in the shared supervision loop
                    deadline = time.monotonic() + delay
                    while time.monotonic() < deadline:
                        if shutdown is not None and \
                                shutdown.requested():
                            terminate_local_procs(procs, grace=30.0)
                            return PREEMPTED_EXIT_CODE
                        time.sleep(min(poll, max(
                            0.0, deadline - time.monotonic())))
                if heartbeat_file:
                    # a fresh heartbeat marks the NEW incarnation live
                    # (and re-seeds a deleted file so detection stays
                    # armed)
                    _seed_heartbeat(heartbeat_file)
                _restart(t, log_dir, preempted=preempted)
                if on_event:
                    on_event('restart', t)
                alive = True
            if not alive:
                return 0
            time.sleep(poll)
    except KeyboardInterrupt:
        terminate_local_procs(procs)
        raise


def supervise(cmd, max_restarts=3, log_dir=None, heartbeat_file=None,
              heartbeat_timeout=None, on_event=None,
              restart_backoff=1.0, restart_backoff_max=30.0):
    """Run ONE worker command under supervision (the per-host elastic
    entry the launcher's --elastic flag uses).  The supervisor itself
    handles SIGTERM gracefully: forward to the worker, let it
    checkpoint, exit PREEMPTED_EXIT_CODE."""
    gs = GracefulShutdown(signals=(signal.SIGTERM,)).install()
    procs = start_local_trainers([cmd], log_dir=log_dir)
    try:
        return watch_local_trainers(
            procs, max_restarts=max_restarts, log_dir=log_dir,
            heartbeat_file=heartbeat_file,
            heartbeat_timeout=heartbeat_timeout, on_event=on_event,
            shutdown=gs, restart_backoff=restart_backoff,
            restart_backoff_max=restart_backoff_max)
    finally:
        gs.uninstall()


