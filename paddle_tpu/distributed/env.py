"""Parallel environment state.

Reference analogue: /root/reference/python/paddle/distributed/parallel.py
(ParallelEnv reads trainer env vars set by launch/spawn).  TPU-native:
"rank" is jax.process_index() for multi-host, and the *logical* rank of
a shard is a mesh-axis coordinate inside shard_map — there are no
per-GPU worker processes on one host.  The global Mesh is the single
source of truth for topology.
"""
import os

import numpy as np

__all__ = ['ParallelEnv', 'get_rank', 'get_world_size', 'get_mesh',
           'set_mesh', 'build_mesh', 'default_mesh_devices']

_global_mesh = None
_recent_real = []   # last ≤2 real meshes set to None, bridges A→None→B


def set_mesh(mesh):
    global _global_mesh, _recent_real
    if mesh is not _global_mesh:
        # bound the eager split() layer cache: correctness comes from
        # the mesh in the cache KEY; eviction only stops unbounded
        # growth across many topologies.  Entries for the incoming and
        # outgoing meshes are KEPT so a program alternating between a
        # train and an aux mesh does not lose trained weights — and
        # meshes torn down via set_mesh(None) (the finally-block
        # pattern the dryruns use) stay in a 2-deep recent window, so
        # A → None → B → None → A keeps A's trained weights too
        from . import mp_ops as _mp_ops
        keep = {mesh, _global_mesh, None} | set(_recent_real)
        for k in [k for k in _mp_ops._LAYER_CACHE
                  if k[-1] not in keep]:
            del _mp_ops._LAYER_CACHE[k]
        if mesh is None and _global_mesh is not None:
            _recent_real = ([_global_mesh]
                            + [m for m in _recent_real
                               if m is not _global_mesh])[:2]
    _global_mesh = mesh


def get_mesh():
    return _global_mesh


def default_mesh_devices():
    import jax
    return jax.devices()


def build_mesh(axes):
    """axes: ordered dict/list of (name, size); size=-1 → infer.

    Returns jax.sharding.Mesh over all visible devices.  Axis order is
    chosen so the LAST axis maps to physically-adjacent devices (ICI
    neighbours in JAX's default device order) — put the
    highest-bandwidth-demand axis (tp) last.
    """
    import jax
    from jax.sharding import Mesh
    items = list(axes.items()) if isinstance(axes, dict) else list(axes)
    devices = np.asarray(jax.devices())
    n = devices.size
    sizes = [s for _, s in items]
    known = int(np.prod([s for s in sizes if s > 0])) or 1
    sizes = [s if s > 0 else n // known for s in sizes]
    need = int(np.prod(sizes))
    if need > n:
        raise ValueError(f"mesh axes {items} need {need} > {n} devices")
    names = tuple(name for name, _ in items)
    return Mesh(devices[:need].reshape(sizes), names)


class ParallelEnv:
    """Reference: paddle.distributed.ParallelEnv."""

    def __init__(self):
        pass

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return int(os.environ.get('FLAGS_selected_tpus', '0'))

    @property
    def device_type(self):
        return 'tpu'

    @property
    def current_endpoint(self):
        return os.environ.get('PADDLE_CURRENT_ENDPOINT', '127.0.0.1:0')

    @property
    def trainer_endpoints(self):
        eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
        return eps.split(',') if eps else ['127.0.0.1:0']


def get_rank():
    """Host process rank (multi-host); inside shard_map use
    collective.get_axis_rank for the logical shard rank."""
    import jax
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def get_world_size():
    mesh = get_mesh()
    if mesh is not None:
        return int(np.prod(list(mesh.shape.values())))
    import jax
    try:
        return jax.device_count()
    except RuntimeError:
        return 1
