"""Sparse-table entry admission configs.

Reference analogue: /root/reference/python/paddle/distributed/entry_attr.py
(ProbabilityEntry:59, CountFilterEntry:100) — the parameter server admits
a new sparse feature row into the table only probabilistically or after a
show-count threshold, bounding table growth on rare features.

TPU-native: the table substitute is incubate.HostOffloadEmbedding (host
DRAM rows, device pulls); these configs gate its HOST-side sparse update
the same way — an unadmitted row keeps its initialization and learns
nothing until admitted.
"""

__all__ = ['EntryAttr', 'ProbabilityEntry', 'CountFilterEntry']


class EntryAttr:
    """Base class for entry admission policies."""

    def __init__(self):
        self._name = None

    def _to_attr(self):
        raise NotImplementedError('EntryAttr is a base class')


class ProbabilityEntry(EntryAttr):
    """Admit each feature row with probability p (decided once per row,
    on its first gradient push)."""

    def __init__(self, probability):
        super().__init__()
        if not isinstance(probability, float):
            raise ValueError('probability must be a float in (0,1)')
        if probability <= 0 or probability >= 1:
            raise ValueError('probability must be a float in (0,1)')
        self._name = 'probability_entry'
        self._probability = probability

    def _to_attr(self):
        return ':'.join([self._name, str(self._probability)])


class CountFilterEntry(EntryAttr):
    """Admit a feature row once it has been seen `count_filter` times."""

    def __init__(self, count_filter):
        super().__init__()
        if not isinstance(count_filter, int):
            raise ValueError('count_filter must be a non-negative integer')
        if count_filter < 0:
            raise ValueError('count_filter must be a non-negative integer')
        self._name = 'count_filter_entry'
        self._count_filter = count_filter

    def _to_attr(self):
        return ':'.join([self._name, str(self._count_filter)])
