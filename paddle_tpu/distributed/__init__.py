"""paddle_tpu.distributed — mesh-based parallelism.

Reference analogue: /root/reference/python/paddle/distributed/ (NCCL
collectives, launch/spawn multi-process workers, fleet).  TPU-native:
one process per HOST drives all local chips through XLA; parallelism is
expressed as shardings over a `jax.sharding.Mesh` and collectives are
compiler-scheduled XLA ops (see collective.py).  `spawn`/`launch` are
therefore thin: they configure the mesh rather than forking per-device
workers.
"""
from . import env  # noqa: F401
from .env import (  # noqa: F401
    ParallelEnv, get_rank, get_world_size, get_mesh, set_mesh, build_mesh)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, all_reduce, all_gather,
    all_gather_object, broadcast, reduce, scatter, alltoall, send, recv,
    barrier, wait, axis_scope, current_axes, p2p_rotate)
from .parallel import (  # noqa: F401
    init_parallel_env, DataParallel)
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import (  # noqa: F401
    save_sharded, load_sharded, CheckpointManager)
from .entry_attr import (  # noqa: F401
    EntryAttr, ProbabilityEntry, CountFilterEntry)
from .mp_ops import split  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401

__all__ = ['ParallelEnv', 'get_rank', 'get_world_size', 'get_mesh',
           'set_mesh', 'build_mesh', 'ReduceOp', 'new_group', 'get_group',
           'all_reduce', 'all_gather', 'broadcast', 'reduce', 'scatter',
           'alltoall', 'send', 'recv', 'barrier', 'wait',
           'init_parallel_env', 'DataParallel', 'fleet', 'spawn', 'launch',
           'save_sharded', 'load_sharded', 'CheckpointManager',
           'EntryAttr', 'ProbabilityEntry', 'CountFilterEntry', 'split',
           'InMemoryDataset', 'QueueDataset']


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Reference: paddle.distributed.spawn forks nprocs GPU workers.
    On TPU one process drives all chips, so spawn configures an
    nprocs-wide mesh and calls func once."""
    init_parallel_env(nprocs if nprocs > 0 else None)
    return func(*args)


# `launch` is a MODULE (like the reference: python -m
# paddle.distributed.launch); importing it here keeps the package
# attribute stable — a function of the same name would be shadowed by
# the submodule import.  Programmatic entry: launch.launch_main(argv).
from . import launch  # noqa: F401,E402
from . import utils  # noqa: F401,E402
