"""Sharded, async checkpointing (orbax-backed) with verified commits.

Reference analogue: /root/reference/python/paddle/framework/io.py:494
(paddle.save of Program+params) plus fleet's per-rank save utils — on
GPU clusters every rank pickles its own shard.  TPU-native: a
mesh-sharded pytree is handed to orbax, which writes per-shard
tensorstore artifacts directly from device memory WITHOUT gathering the
full state onto one host, and (async mode) overlaps the device→disk
copy with the next training steps.  Restore takes an abstract template
(shapes/dtypes/NamedShardings) and materializes each leaf directly into
its mesh placement.

Crash-safety (resilience.manifest): a save only COUNTS once its commit
manifest (step + per-file sizes/checksums) lands atomically after the
async barrier.  `latest_step()` is the latest *committed* step; a
SIGKILL mid-save leaves an uncommitted dir that readers simply never
see, and a committed dir whose contents fail verification is
quarantined (renamed aside, never silently loaded) while restore
falls back to the previous committed step.  Multi-host saves commit in
two phases (per-host intent/ack files, process-0 finalize only after
every ack — see resilience.manifest.finalize_two_phase), and restore
onto a DIFFERENT mesh/process count reshards the committed arrays onto
the new placement (elastic reshape — a preempted pool resumes
smaller), logged as a ``reshape_restore`` telemetry event.

    save_sharded(tree, path, async_save=True)   -> wait() handle
    load_sharded(path, like=tree_or_abstract)   -> restored pytree
    CheckpointManager(dir, keep)                -> step-level save/
                                                   restore/latest

The pickle path (framework/io.py) remains for small host-side
state_dicts; this module is the 1.3B-scale path.
"""
import os
import warnings

import jax
import numpy as np

from ..resilience import manifest as _manifest

__all__ = ['save_sharded', 'load_sharded', 'CheckpointManager',
           'save_host_shard', 'load_host_shard',
           'latest_committed_step']


def _checkpointer(async_save):
    import orbax.checkpoint as ocp
    handler = ocp.StandardCheckpointHandler()
    if async_save:
        return ocp.AsyncCheckpointer(handler)
    return ocp.Checkpointer(handler)


class _SaveHandle:
    """Completion handle for one save.  wait() is idempotent: the
    first successful call drains the async barrier, closes the
    checkpointer, and commits the manifest; later calls are no-ops
    (the old behaviour re-entered a closed checkpointer).  A wait()
    that RAISES may be retried: each sub-step (drain+close, commit)
    runs at most once, so a transient commit failure is retryable
    without double-closing."""

    def __init__(self, ckptr, on_commit=None, step=None):
        self._ckptr = ckptr
        self._on_commit = on_commit
        self._drained = False
        self._done = False
        self._step = step

    def wait(self):
        if self._done:
            return
        import time as _time
        t0 = _time.perf_counter()
        if not self._drained:
            if hasattr(self._ckptr, 'wait_until_finished'):
                self._ckptr.wait_until_finished()
            self._ckptr.close()
            self._drained = True
        if self._on_commit is not None:
            self._on_commit()
        self._done = True
        from ..telemetry import event as _tevent
        _tevent('checkpoint_commit', step=self._step,
                dur_s=round(_time.perf_counter() - t0, 6))

    @property
    def committed(self):
        return self._done


def _tree_topology(tree):
    """{'mesh': axis-size dict, 'process_count': N} recorded in the
    commit manifest — the reshape-restore path reads it back to log
    that a checkpoint saved under dp=8 is being resharded onto a
    smaller pool."""
    meta = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        mesh = getattr(getattr(leaf, 'sharding', None), 'mesh', None)
        shape = getattr(mesh, 'shape', None)
        if shape:
            meta['mesh'] = dict(shape)
            break
    try:
        meta['process_count'] = jax.process_count()
    except RuntimeError:
        pass
    return meta


def save_sharded(tree, path, async_save=True, overwrite=True,
                 commit=True, step=None, checksums=True,
                 two_phase=None, num_hosts=None, barrier_timeout=120.0):
    """Write a (possibly mesh-sharded) pytree of jax.Arrays as per-shard
    artifacts under `path`.  Returns a handle; call .wait() before
    relying on the files (async mode overlaps with compute until then).
    With `commit` (default) wait() also writes the commit manifest that
    marks the directory as a finished, verifiable checkpoint.
    `checksums=False` commits presence+sizes only — still catches every
    crash-shaped tear without re-reading multi-GB shards inside the
    post-save barrier (see resilience.manifest.write_manifest).

    Multi-host runs commit in TWO PHASES (resilience.manifest): every
    process's wait() writes an intent/ack recording that its shards are
    durable, and process 0 writes the final manifest only after every
    host's ack arrived (bounded by `barrier_timeout`) — process 0
    finishing its own save proves nothing about host 7's, and the old
    single-phase commit could certify a checkpoint whose remote shards
    were still in flight.  `two_phase` defaults to process_count > 1;
    tests force it with an explicit `num_hosts` to simulate a pod in
    one process.  A SIGKILL between the phases leaves acks but no
    manifest: uncommitted, and quarantined as half-committed once the
    acks go stale (see CheckpointManager.restore).
    """
    import time as _time
    import orbax.checkpoint as ocp
    from ..telemetry import event as _tevent
    path = os.path.abspath(path)
    try:
        proc, nprocs = jax.process_index(), jax.process_count()
    except RuntimeError:
        proc, nprocs = 0, 1
    if two_phase is None:
        two_phase = nprocs > 1
    hosts = int(num_hosts) if num_hosts is not None else nprocs
    ckptr = _checkpointer(async_save)
    _t0 = _time.perf_counter()
    ckptr.save(path, args=ocp.args.StandardSave(tree), force=overwrite)
    # async mode: dispatch_s is the synchronous cost the step loop
    # paid; the device→disk copy overlaps later compute and its drain
    # is timed by the checkpoint_commit event in _SaveHandle.wait()
    _tevent('checkpoint_save', step=step, path=path,
            async_save=bool(async_save),
            dispatch_s=round(_time.perf_counter() - _t0, 6))
    on_commit = None
    if commit:
        # leaf_spec must be computed from the SAME abstraction
        # restore will compare against (_abstractify), or python
        # scalar leaves record dtype 'int' at save but 'int32' at
        # restore and a valid checkpoint fails the template check;
        # computed eagerly — by commit time the arrays may be
        # donated away
        spec_tree = _abstractify(tree)
        meta = _tree_topology(tree)
        if two_phase:
            def on_commit():
                # phase 1: THIS host's shards are durable (we are past
                # the save barrier).  Phase 2 runs on process 0 only.
                _manifest.write_intent(path, proc, step=step,
                                       files=(), checksums=checksums)
                if proc == 0:
                    _manifest.finalize_two_phase(
                        path, hosts, step=step, tree=spec_tree,
                        checksums=checksums, meta=meta,
                        timeout=barrier_timeout)
        elif proc == 0:
            # single-host fast path: one atomic manifest, no barrier
            on_commit = lambda: _manifest.write_manifest(  # noqa: E731
                path, step=step, tree=spec_tree, checksums=checksums,
                meta=meta)
    handle = _SaveHandle(ckptr, on_commit=on_commit, step=step)
    if not async_save:
        handle.wait()
    return handle


def _abstractify(like):
    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        sharding = getattr(x, 'sharding', None)
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype
                                    if not hasattr(x, 'dtype') else x.dtype,
                                    sharding=sharding)
    return jax.tree_util.tree_map(leaf, like)


def save_host_shard(run_dir, step, host, arrays, num_hosts,
                    prefix='step', finalize=None, checksums=True,
                    barrier_timeout=120.0, meta=None):
    """Per-HOST shard save with the cross-host two-phase commit — the
    multi-process checkpoint path for clusters where one orbax save
    cannot span the processes (the CPU backend runs no cross-process
    computations; host-local state has the same shape on real pods).

    Each host writes ``<run_dir>/<prefix>_<step>/shard_r<host>.npz``
    through resilience.manifest.atomic_write (the chaos file seam
    covers it: torn/EIO writes hit this exactly as they hit orbax
    manifests), then acks with a phase-1 intent.  Host 0 (`finalize`
    overrides) finalizes the two-phase commit once every host's ack
    landed, recording ``process_count`` so ``check_ckpt --deep
    --cluster`` can audit the rank set.  Raises CommitBarrierTimeout
    from the finalizer when an ack never arrives (a killed worker) —
    the directory then stays uncommitted and is swept later, exactly
    like the orbax path.  Emits the same checkpoint telemetry.

    Returns the manifest doc on the finalizing host, else None."""
    import io
    import time as _time
    from ..telemetry import event as _tevent
    host = int(host)
    num_hosts = int(num_hosts)
    if finalize is None:
        finalize = host == 0
    step_dir = os.path.join(os.path.abspath(run_dir),
                            f'{prefix}_{step}')
    os.makedirs(step_dir, exist_ok=True)
    rel = f'shard_r{host}.npz'
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
    payload = buf.getvalue()
    _t0 = _time.perf_counter()
    _manifest.atomic_write(os.path.join(step_dir, rel),
                           lambda f: f.write(payload), mode='wb',
                           prefix='.shard_tmp')
    _tevent('checkpoint_save', step=step, path=step_dir,
            async_save=False, host=host,
            dispatch_s=round(_time.perf_counter() - _t0, 6))
    _manifest.write_intent(step_dir, host, step=step, files=[rel],
                           checksums=checksums)
    if not finalize:
        return None
    full_meta = {'process_count': num_hosts}
    full_meta.update(meta or {})
    doc = _manifest.finalize_two_phase(
        step_dir, num_hosts, step=step, checksums=checksums,
        meta=full_meta, timeout=barrier_timeout)
    _tevent('checkpoint_commit', step=step, host=host, dur_s=None)
    return doc


def load_host_shard(run_dir, step, host, prefix='step'):
    """This host's shard dict from a COMMITTED per-host step dir, or
    None (absent / uncommitted / unreadable — the caller falls back to
    an older step or a cold start)."""
    step_dir = os.path.join(os.path.abspath(run_dir),
                            f'{prefix}_{step}')
    if not _manifest.is_committed(step_dir):
        return None
    p = os.path.join(step_dir, f'shard_r{int(host)}.npz')
    try:
        with np.load(p) as z:
            return {k: z[k].copy() for k in z.files}
    except (OSError, ValueError):
        return None


def latest_committed_step(run_dir, prefix='step'):
    """Newest COMMITTED step id under `run_dir`, or -1 — the reader
    view shared by every worker of a multi-process cluster (each then
    loads its own shard with load_host_shard)."""
    best = -1
    try:
        names = os.listdir(os.path.abspath(run_dir))
    except OSError:
        return best
    for f in names:
        tag = f[len(prefix) + 1:]
        if not (f.startswith(prefix + '_') and tag.isdigit()):
            continue
        if _manifest.is_committed(os.path.join(run_dir, f)):
            best = max(best, int(tag))
    return best


def load_sharded(path, like):
    """Restore a pytree saved by save_sharded.  `like` supplies the
    structure + per-leaf shape/dtype/sharding (live arrays or
    jax.ShapeDtypeStruct with .sharding set); each leaf lands directly
    on its mesh shards."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = _checkpointer(False)
    try:
        return ckptr.restore(
            path, args=ocp.args.StandardRestore(_abstractify(like)))
    finally:
        ckptr.close()


class CheckpointManager:
    """Step-level sharded checkpoint rotation — the elastic/failure
    recovery path (SURVEY §5 A3) at model scale.  save() is async by
    default: step N+1 computes while step N's shards hit disk.

    Only COMMITTED steps (valid manifest, see resilience.manifest) are
    visible to latest_step()/restore(); restore() further verifies the
    manifest's sizes/checksums and walks back to the previous committed
    step when a directory turns out torn, renaming the torn dir aside
    (quarantine) so it is preserved for forensics but never selected
    again."""

    def __init__(self, directory, keep=3, prefix='step', async_save=True,
                 verify=True, checksums=True, two_phase=None,
                 num_hosts=None, barrier_timeout=120.0,
                 half_commit_grace=300.0):
        # checksums=False: commit sizes only — the hashing otherwise
        # runs inside wait()'s post-save barrier (i.e. at the head of
        # the NEXT save), a full re-read of the checkpoint that can
        # eat the async overlap at multi-GB scale; sizes still catch
        # every crash-shaped tear
        self.directory = os.path.abspath(directory)
        self.keep = keep
        self.prefix = prefix
        self.async_save = async_save
        self.verify = verify
        self.checksums = checksums
        # cross-host two-phase commit knobs (see save_sharded); a dir
        # holding 2PC acks but no manifest for longer than
        # half_commit_grace seconds is a half-committed save whose
        # finalizer died between the phases — quarantineable, since
        # acks land only after every writer's save barrier
        self.two_phase = two_phase
        self.num_hosts = num_hosts
        self.barrier_timeout = barrier_timeout
        self.half_commit_grace = half_commit_grace
        self._pending = None
        self._pending_step = None
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, step):
        return os.path.join(self.directory, f'{self.prefix}_{step}')

    def _steps(self, committed=True):
        """Step ids present on disk, ascending.  committed=True (the
        default and the only safe reader view) filters to dirs whose
        commit manifest landed; committed=False additionally includes
        torn/in-flight dirs — writer-side bookkeeping only."""
        out = []
        for f in os.listdir(self.directory):
            tag = f[len(self.prefix) + 1:]
            if not (f.startswith(self.prefix + '_') and tag.isdigit()):
                continue
            if committed and not _manifest.is_committed(self._path(int(tag))):
                continue
            out.append(int(tag))
        return sorted(out)

    def save(self, tree, step):
        self.wait()  # one in-flight save at a time
        handle = save_sharded(tree, self._path(step),
                              async_save=self.async_save, step=step,
                              checksums=self.checksums,
                              two_phase=self.two_phase,
                              num_hosts=self.num_hosts,
                              barrier_timeout=self.barrier_timeout)
        if not self.async_save:
            self._prune()
            return handle
        self._pending = handle
        self._pending_step = step
        return handle

    def wait(self):
        if self._pending is not None:
            self._pending.wait()
            self._pending = None
            self._pending_step = None
            self._prune()

    def _prune(self):
        """Rotate out old COMMITTED checkpoints beyond `keep`.
        Uncommitted dirs are never pruned here: the newest may be an
        in-flight async save (ours or another process's), and torn
        ones are quarantined — not destroyed — by restore()."""
        import shutil
        for s in self._steps(committed=True)[:-self.keep]:
            shutil.rmtree(self._path(s), ignore_errors=True)

    def _quarantine(self, step):
        """Move a torn step dir aside (never delete: a human may want
        the shards) under a non-step name so every lister skips it."""
        src = self._path(step)
        for k in range(100):
            dst = f'{src}.torn-{k}'
            if not os.path.exists(dst):
                try:
                    os.replace(src, dst)
                    from ..telemetry import event as _tevent
                    _tevent('checkpoint_quarantine', step=step,
                            path=src, moved_to=dst)
                    return dst
                except OSError:
                    break
        return None

    def latest_step(self):
        """Newest COMMITTED step, or -1.  A directory whose async save
        died before its manifest landed does not exist for readers."""
        steps = self._steps(committed=True)
        return steps[-1] if steps else -1

    def restore(self, like, step=None, verify=None):
        """Restore `step` (default: latest committed).  Returns
        (tree, step) or (None, -1) when no committed checkpoint exists.

        Each candidate's manifest is verified (file presence + sizes +
        checksums) before orbax touches it; a torn candidate is
        quarantined and the previous committed step is tried — restore
        degrades to older data, never crashes on (or silently loads)
        partial state."""
        verify = self.verify if verify is None else verify
        try:
            # AOT warm start: a precompile sidecar manifest in the run
            # dir (tools/precompile.py) pre-loads the exported step
            # modules, so the restore target's first compile lookups
            # deserialize instead of re-tracing
            from ..core import compile_cache
            compile_cache.warm_start(self.directory,
                                     name='CheckpointManager')
        except Exception:
            pass
        self._sweep_half_committed()
        if step is not None:
            candidates = [step] + [s for s in
                                   reversed(self._steps(committed=True))
                                   if s < step]
        else:
            candidates = list(reversed(self._steps(committed=True)))
        if not candidates:
            uncommitted = self._steps(committed=False)
            if uncommitted:
                # pre-manifest-era (or torn) step dirs exist but none
                # are restorable — say so, or an upgraded job silently
                # restarts from step 0 discarding all prior progress
                warnings.warn(
                    f'{len(uncommitted)} step dir(s) under '
                    f'{self.directory} have no commit manifest '
                    '(written before verified checkpoints, or torn); '
                    'none are restorable as-is — inspect with '
                    'tools/check_ckpt.py and adopt trusted dirs with '
                    '--adopt', RuntimeWarning, stacklevel=2)
        for s in candidates:
            path = self._path(s)
            if not os.path.isdir(path):
                if s == step:
                    # the EXPLICITLY requested step is absent — say so
                    # before quietly degrading to older data (a typo'd
                    # step number should be visible, not absorbed)
                    warnings.warn(
                        f'requested checkpoint step {step} does not '
                        f'exist under {self.directory}; falling back '
                        'to previous committed step',
                        RuntimeWarning, stacklevel=2)
                continue
            if s == self._pending_step:
                # our own async save is still in flight — not torn,
                # just not finished; it cannot be restored yet
                continue
            doc = _manifest.read_manifest(path)
            if doc is None:
                # no manifest: either a kill-between-save-and-commit
                # artifact or ANOTHER process's in-flight save — the
                # two are indistinguishable from here, so never
                # quarantine (renaming a live save out from under its
                # writer would corrupt it); just skip.  (Dirs whose
                # 2PC acks went STALE were already quarantined by the
                # _sweep_half_committed pass.)
                warnings.warn(
                    f'checkpoint {path} has no commit manifest (torn '
                    'or in-flight); falling back to previous '
                    'committed step', RuntimeWarning, stacklevel=2)
                continue
            if verify:
                ok, errors = _manifest.verify_manifest(path)
                if not ok:
                    # manifest present but contents mismatch: the
                    # commit DID land, so nobody is still writing —
                    # this is real corruption, safe to move aside
                    moved = self._quarantine(s)
                    warnings.warn(
                        f'checkpoint {path} failed verification '
                        f'({errors[:3]}{"..." if len(errors) > 3 else ""})'
                        + (f'; quarantined to {moved}' if moved else '')
                        + '; falling back to previous committed step',
                        RuntimeWarning, stacklevel=2)
                    continue
            if doc.get('leaf_spec'):
                # wrong-template restore is a CALLER bug, not a torn
                # checkpoint: fail fast with named leaves (falling
                # back would hit the same mismatch on older steps)
                diffs = _manifest.spec_mismatches(
                    doc['leaf_spec'],
                    _manifest.leaf_spec(_abstractify(like)))
                if diffs:
                    raise ValueError(
                        f'restore template does not match checkpoint '
                        f'{path}: ' + '; '.join(diffs[:5])
                        + ('...' if len(diffs) > 5 else ''))
            self._note_reshape(doc, like, s)
            from ..telemetry import span as _tspan
            with _tspan('checkpoint_restore', step=s, path=path):
                tree = load_sharded(path, like)
            return tree, s
        return None, -1

    def _sweep_half_committed(self):
        """Quarantine UNCOMMITTED step dirs whose two-phase acks went
        stale.  Acks land only after every writer's save barrier, so
        stale acks + no manifest can only mean the finalizer died
        between intent and finalize — nobody is still writing, and
        leaving the dir around would shadow the real latest step
        forever.  Dirs with fresh acks (finalize may be in flight) or
        no acks at all (single-phase in-flight save) are never
        touched."""
        committed = set(self._steps(committed=True))
        for s in self._steps(committed=False):
            if s in committed or s == self._pending_step:
                continue
            path = self._path(s)
            age = _manifest.intent_age(path)
            if age is None or age <= self.half_commit_grace:
                continue
            moved = self._quarantine(s)
            warnings.warn(
                f'checkpoint {path} is half-committed (2-phase acks '
                f'{age:.0f}s stale, no final manifest — finalizer '
                'died between intent and finalize)'
                + (f'; quarantined to {moved}' if moved else '')
                + '; falling back to previous committed step',
                RuntimeWarning, stacklevel=3)

    @staticmethod
    def _note_reshape(doc, like, step):
        """Elastic reshape restore: the manifest records the SAVING
        topology (mesh axis sizes + process count); when the restore
        template's mesh differs — a preempted dp=8 pool resuming as
        dp=4 — orbax reshards each leaf from the committed tensorstore
        data onto the new placement.  That is correct but operationally
        loud-worthy, so it lands in telemetry as ``reshape_restore``."""
        saved_mesh = doc.get('mesh')
        saved_procs = doc.get('process_count')
        cur = _tree_topology(like)
        cur_mesh, cur_procs = cur.get('mesh'), cur.get('process_count')
        mesh_changed = (saved_mesh is not None and cur_mesh is not None
                        and saved_mesh != cur_mesh)
        procs_changed = (saved_procs is not None
                         and cur_procs is not None
                         and saved_procs != cur_procs)
        if mesh_changed or procs_changed:
            from ..telemetry import event as _tevent
            _tevent('reshape_restore', step=step,
                    saved_mesh=saved_mesh, mesh=cur_mesh,
                    saved_process_count=saved_procs,
                    process_count=cur_procs)
