"""Sharded, async checkpointing (orbax-backed).

Reference analogue: /root/reference/python/paddle/framework/io.py:494
(paddle.save of Program+params) plus fleet's per-rank save utils — on
GPU clusters every rank pickles its own shard.  TPU-native: a
mesh-sharded pytree is handed to orbax, which writes per-shard
tensorstore artifacts directly from device memory WITHOUT gathering the
full state onto one host, and (async mode) overlaps the device→disk
copy with the next training steps.  Restore takes an abstract template
(shapes/dtypes/NamedShardings) and materializes each leaf directly into
its mesh placement.

    save_sharded(tree, path, async_save=True)   -> wait() handle
    load_sharded(path, like=tree_or_abstract)   -> restored pytree
    CheckpointManager(dir, keep)                -> step-level save/
                                                   restore/latest

The pickle path (framework/io.py) remains for small host-side
state_dicts; this module is the 1.3B-scale path.
"""
import os

import jax
import numpy as np

__all__ = ['save_sharded', 'load_sharded', 'CheckpointManager']


def _checkpointer(async_save):
    import orbax.checkpoint as ocp
    handler = ocp.StandardCheckpointHandler()
    if async_save:
        return ocp.AsyncCheckpointer(handler)
    return ocp.Checkpointer(handler)


class _SaveHandle:
    def __init__(self, ckptr):
        self._ckptr = ckptr

    def wait(self):
        if hasattr(self._ckptr, 'wait_until_finished'):
            self._ckptr.wait_until_finished()
        self._ckptr.close()


def save_sharded(tree, path, async_save=True, overwrite=True):
    """Write a (possibly mesh-sharded) pytree of jax.Arrays as per-shard
    artifacts under `path`.  Returns a handle; call .wait() before
    relying on the files (async mode overlaps with compute until then).
    """
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = _checkpointer(async_save)
    ckptr.save(path, args=ocp.args.StandardSave(tree), force=overwrite)
    handle = _SaveHandle(ckptr)
    if not async_save:
        handle.wait()
    return handle


def _abstractify(like):
    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        sharding = getattr(x, 'sharding', None)
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype
                                    if not hasattr(x, 'dtype') else x.dtype,
                                    sharding=sharding)
    return jax.tree_util.tree_map(leaf, like)


def load_sharded(path, like):
    """Restore a pytree saved by save_sharded.  `like` supplies the
    structure + per-leaf shape/dtype/sharding (live arrays or
    jax.ShapeDtypeStruct with .sharding set); each leaf lands directly
    on its mesh shards."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = _checkpointer(False)
    try:
        return ckptr.restore(
            path, args=ocp.args.StandardRestore(_abstractify(like)))
    finally:
        ckptr.close()


class CheckpointManager:
    """Step-level sharded checkpoint rotation — the elastic/failure
    recovery path (SURVEY §5 A3) at model scale.  save() is async by
    default: step N+1 computes while step N's shards hit disk."""

    def __init__(self, directory, keep=3, prefix='step', async_save=True):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        self.prefix = prefix
        self.async_save = async_save
        self._pending = None
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, step):
        return os.path.join(self.directory, f'{self.prefix}_{step}')

    def _steps(self):
        out = []
        for f in os.listdir(self.directory):
            tag = f[len(self.prefix) + 1:]
            if f.startswith(self.prefix + '_') and tag.isdigit():
                out.append(int(tag))
        return sorted(out)

    def save(self, tree, step):
        self.wait()  # one in-flight save at a time
        self._pending = save_sharded(tree, self._path(step),
                                     async_save=self.async_save)
        if not self.async_save:
            self._prune()
        return self._pending

    def wait(self):
        if self._pending is not None:
            self._pending.wait()
            self._pending = None
            self._prune()

    def _prune(self):
        import shutil
        for s in self._steps()[:-self.keep]:
            shutil.rmtree(self._path(s), ignore_errors=True)

    def latest_step(self):
        steps = self._steps()
        return steps[-1] if steps else -1

    def restore(self, like, step=None):
        """Restore `step` (default: latest).  Returns (tree, step) or
        (None, -1) when no checkpoint exists."""
        if step is None:
            step = self.latest_step()
        if step < 0 or not os.path.isdir(self._path(step)):
            return None, -1
        return load_sharded(self._path(step), like), step
