"""Collective communication ops.

Reference analogue: /root/reference/python/paddle/distributed/collective.py
+ the C++ c_allreduce/c_allgather/... NCCL ops in
paddle/fluid/operators/collective/.  TPU-native: a collective is NOT a
runtime call into a comm library — it is an XLA op (`lax.psum`,
`lax.all_gather`, `lax.ppermute`, `lax.all_to_all`) that the compiler
schedules onto ICI links, overlapping with compute.  These functions are
therefore *trace-time* constructs: inside a `shard_map` region (entered
by paddle_tpu's parallel engines) they lower to the XLA collective over
the bound mesh axis; outside any parallel region they are the identity
(world of one replica), which keeps single-chip code runnable unchanged.

Process groups: a reference `Group` names a NCCL communicator subset; a
paddle_tpu `Group` names a SET OF MESH AXES — e.g. the dp group is axis
('dp',), the mp group axis ('tp',).  XLA derives the participant subsets
from the mesh, which is how sub-groups ride ICI instead of host loops.
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.dispatch import apply
from . import env as _env

__all__ = ['ReduceOp', 'Group', 'new_group', 'get_group', 'all_reduce',
           'all_gather', 'all_gather_object', 'broadcast', 'reduce',
           'scatter', 'alltoall', 'send', 'recv', 'barrier', 'wait',
           'axis_scope', 'current_axes', 'get_axis_rank', 'split_group']


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communicator = a tuple of mesh axis names."""

    def __init__(self, id, axes, ranks=None):
        self.id = id
        self.axes = tuple(axes)
        self.ranks = ranks
        self.nranks = -1  # resolved against mesh at use time

    @property
    def name(self):
        return f"group_{self.id}:{','.join(self.axes)}"

    def __repr__(self):
        return f"Group(id={self.id}, axes={self.axes})"


_groups = {}
_next_gid = 1


def _world_group():
    mesh = _env.get_mesh()
    axes = tuple(mesh.axis_names) if mesh is not None else ()
    return Group(0, axes)


def get_group(gid=0):
    if gid == 0:
        return _world_group()
    return _groups[gid]


def new_group(ranks=None, backend=None, axes=None):
    """Create a group.  TPU-native callers pass `axes=('dp',)`; the
    reference rank-list form is accepted and maps to the world group's
    axes when it covers all ranks (arbitrary rank subsets that do not
    correspond to a mesh sub-axis are not representable on ICI)."""
    global _next_gid
    gid = _next_gid
    _next_gid += 1
    if axes is None:
        axes = _world_group().axes
    g = Group(gid, axes, ranks)
    _groups[gid] = g
    return g


# -- axis scope: which mesh axes are live inside the current shard_map ----

_axis_stack = []


@contextlib.contextmanager
def axis_scope(*names):
    """Entered by parallel engines around shard_map'd bodies so eager-API
    collectives in user code resolve their mesh axis."""
    _axis_stack.append(tuple(names))
    try:
        yield
    finally:
        _axis_stack.pop()


def current_axes():
    return _axis_stack[-1] if _axis_stack else ()


def _resolve_axes(group):
    live = current_axes()
    if not live:
        return ()
    if group is None or group == 0:
        return live
    axes = group.axes if isinstance(group, Group) else tuple(group)
    return tuple(a for a in axes if a in live)


def get_axis_rank(axis):
    """Logical coordinate along `axis` (only inside a parallel region)."""
    if axis in current_axes():
        return lax.axis_index(axis)
    return 0


def _unwrap(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def _rewrap(x, val):
    if isinstance(x, Tensor):
        x.value = val
        return x
    return Tensor._from_value(val)


# -- collectives -------------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    def fn(v):
        if op == ReduceOp.SUM:
            return lax.psum(v, axes)
        if op == ReduceOp.MAX:
            return lax.pmax(v, axes)
        if op == ReduceOp.MIN:
            return lax.pmin(v, axes)
        if op == ReduceOp.AVG:
            return lax.pmean(v, axes)
        if op == ReduceOp.PROD:
            g = lax.all_gather(v, axes[0] if len(axes) == 1 else axes,
                               axis=0, tiled=False)
            return jnp.prod(g, axis=0)
        raise ValueError(f"bad ReduceOp {op}")
    out = apply(fn, tensor if isinstance(tensor, Tensor)
                else Tensor._from_value(_unwrap(tensor)),
                op_name='all_reduce')
    # reference mutates in place
    return _rewrap(tensor, out.value if isinstance(out, Tensor) else out)


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """tensor_list (out): filled with per-rank shards; returns the
    concatenated array as well (TPU-friendly return form)."""
    axes = _resolve_axes(group)
    v = _unwrap(tensor)
    if not axes:
        if isinstance(tensor_list, list):
            tensor_list.append(_rewrap(None, v) if not isinstance(tensor, Tensor)
                               else Tensor._from_value(v))
        return Tensor._from_value(v)
    name = axes[0] if len(axes) == 1 else axes
    gathered = lax.all_gather(v, name, axis=0, tiled=False)
    n = gathered.shape[0]
    if isinstance(tensor_list, list):
        for i in range(n):
            tensor_list.append(Tensor._from_value(gathered[i]))
    return Tensor._from_value(
        jnp.concatenate([gathered[i] for i in range(n)], axis=axis)
        if axis != 0 else gathered.reshape((-1,) + v.shape[1:]))


def all_gather_object(obj_list, obj, group=None):
    """Host-side object gather — single-process world: identity."""
    obj_list.append(obj)
    return obj_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    v = _unwrap(tensor)
    name = axes[0]
    idx = lax.axis_index(name)
    # select src's value, then sum (XLA lowers this to a broadcast);
    # where() not v*mask so inf/NaN on non-src ranks cannot pollute
    out = lax.psum(jnp.where(idx == src, v, jnp.zeros_like(v)), name)
    return _rewrap(tensor, out)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    v = _unwrap(tensor)
    name = axes[0]
    summed = lax.psum(v, name) if op == ReduceOp.SUM else (
        lax.pmax(v, name) if op == ReduceOp.MAX else lax.pmin(v, name))
    idx = lax.axis_index(name)
    out = jnp.where(idx == dst, summed, v)
    return _rewrap(tensor, out)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        if tensor_list:
            return _rewrap(tensor, _unwrap(tensor_list[0]))
        return tensor
    name = axes[0]
    stacked = jnp.stack([_unwrap(t) for t in tensor_list], axis=0)
    full = broadcast(Tensor._from_value(stacked), src=src, group=group)
    idx = lax.axis_index(name)
    out = lax.dynamic_index_in_dim(full.value, idx, axis=0, keepdims=False)
    return _rewrap(tensor, out)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        outs = [Tensor._from_value(_unwrap(t)) for t in in_tensor_list]
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(outs)
        return outs
    name = axes[0]
    x = jnp.stack([_unwrap(t) for t in in_tensor_list], axis=0)
    y = lax.all_to_all(x, name, split_axis=0, concat_axis=0, tiled=False)
    outs = [Tensor._from_value(y[i]) for i in range(y.shape[0])]
    if isinstance(out_tensor_list, list):
        out_tensor_list.extend(outs)
    return outs


def p2p(tensor, src, dst, group=None):
    """Single matched send/recv pair: rank `dst` receives rank `src`'s
    tensor; every other rank receives zeros.  lax.ppermute with one
    (src, dst) pair — the SPMD form of an NCCL send/recv pair."""
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    out = lax.ppermute(_unwrap(tensor), axes[0], [(src, dst)])
    return _rewrap(tensor, out)


def send(tensor, dst=0, group=None, sync_op=True, src=None):
    """Point-to-point send.  SPMD programs have no per-rank control
    flow, so the sender rank must be explicit: pass `src` (then this is
    p2p(src→dst)), or use p2p_rotate for the ring pattern the
    reference's pipeline engine builds out of send/recv."""
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    if src is None:
        raise ValueError(
            "send() inside an SPMD region needs src= (every rank runs "
            "this line); use p2p(tensor, src, dst) or p2p_rotate()")
    return p2p(tensor, src, dst, group)


def recv(tensor, src=0, group=None, sync_op=True, dst=None):
    """Point-to-point receive; pairs with send(). With only `src` given,
    all ranks receive src's value (a broadcast, matching how reference
    code typically consumes recv)."""
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    if dst is not None:
        return p2p(tensor, src, dst, group)
    return broadcast(tensor, src=src, group=group)


def p2p_rotate(tensor, group=None, shift=1):
    """Ring rotation: rank i → rank (i+shift)%n.  The TPU-native
    primitive behind pipeline microbatch handoff and ring attention."""
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    name = axes[0]
    n = _axis_size(name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    out = lax.ppermute(_unwrap(tensor), name, perm)
    return _rewrap(tensor, out)


def _axis_size(name):
    mesh = _env.get_mesh()
    if mesh is not None and name in mesh.shape:
        return mesh.shape[name]
    return lax.psum(1, name)


def barrier(group=None):
    """XLA programs are bulk-synchronous per step; barrier is only
    meaningful host-side (multi-host sync)."""
    try:
        import jax.experimental.multihost_utils as mh
        if jax.process_count() > 1:
            mh.sync_global_devices('paddle_tpu_barrier')
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    v = _unwrap(tensor)
    if hasattr(v, 'block_until_ready'):
        v.block_until_ready()
    return tensor


def split_group(mesh_axis):
    """Convenience: the Group for one mesh axis."""
    return new_group(axes=(mesh_axis,))
