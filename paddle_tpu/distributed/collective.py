"""Collective communication ops.

Reference analogue: /root/reference/python/paddle/distributed/collective.py
+ the C++ c_allreduce/c_allgather/... NCCL ops in
paddle/fluid/operators/collective/.  TPU-native: a collective is NOT a
runtime call into a comm library — it is an XLA op (`lax.psum`,
`lax.all_gather`, `lax.ppermute`, `lax.all_to_all`) that the compiler
schedules onto ICI links, overlapping with compute.  These functions are
therefore *trace-time* constructs: inside a `shard_map` region (entered
by paddle_tpu's parallel engines) they lower to the XLA collective over
the bound mesh axis; outside any parallel region they are the identity
(world of one replica), which keeps single-chip code runnable unchanged.

Process groups: a reference `Group` names a NCCL communicator subset; a
paddle_tpu `Group` names a SET OF MESH AXES — e.g. the dp group is axis
('dp',), the mp group axis ('tp',).  XLA derives the participant subsets
from the mesh, which is how sub-groups ride ICI instead of host loops.

HOST TRANSPORT (multi-process, outside any mesh region): XLA cannot run
one computation across processes on the CPU backend, and even on TPU
some collectives are host-side by nature (object gathers, commit
barriers, control-plane consensus).  :class:`HostCollectives` is that
layer: a key-value transport over a pluggable client — jax's
coordination-service client on a real pod (``jax.distributed``
initialized), or a :class:`FileKVStore` over a shared directory for the
multi-process chaos topology, where a SIGKILLed worker must be able to
restart and REJOIN (the coordination service cannot re-admit a dead
task; files can).  Every payload travels with an explicit dtype/shape/
crc32 header, so the wire format is dtype-agnostic: an int8 or packed
int4 quantized payload (EQuARX) is framed and verified identically to
f32.  Every blocking wait is deadline-bounded and polls the cluster
abort flag — a dead or hung peer surfaces as :class:`CollectiveTimeout`
or :class:`CoordinatedAbort`, never as an infinite wait.  These are the
collective-layer fault seams resilience.chaos injects into.
"""
import binascii
import collections
import contextlib
import json
import os
import pickle
import sys
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.dispatch import apply
from . import env as _env

__all__ = ['ReduceOp', 'Group', 'new_group', 'get_group', 'all_reduce',
           'all_gather', 'all_gather_object', 'broadcast', 'reduce',
           'scatter', 'alltoall', 'send', 'recv', 'barrier', 'wait',
           'axis_scope', 'current_axes', 'get_axis_rank', 'split_group',
           'FileKVStore', 'HostCollectives', 'CollectiveTimeout',
           'CollectivePayloadError', 'CoordinatedAbort',
           'get_kv_client', 'set_kv_client', 'KV_ENV',
           'CollectiveLedger', 'get_ledger', 'reset_ledgers',
           'diff_ledgers', 'probe_mismatch', 'ledger_enabled',
           'LEDGER_KEY', 'LEDGER_ENV']


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communicator = a tuple of mesh axis names."""

    def __init__(self, id, axes, ranks=None):
        self.id = id
        self.axes = tuple(axes)
        self.ranks = ranks
        self.nranks = -1  # resolved against mesh at use time

    @property
    def name(self):
        return f"group_{self.id}:{','.join(self.axes)}"

    def __repr__(self):
        return f"Group(id={self.id}, axes={self.axes})"


_groups = {}
_next_gid = 1


def _world_group():
    mesh = _env.get_mesh()
    axes = tuple(mesh.axis_names) if mesh is not None else ()
    return Group(0, axes)


def get_group(gid=0):
    if gid == 0:
        return _world_group()
    return _groups[gid]


def new_group(ranks=None, backend=None, axes=None):
    """Create a group.  TPU-native callers pass `axes=('dp',)`; the
    reference rank-list form is accepted and maps to the world group's
    axes when it covers all ranks (arbitrary rank subsets that do not
    correspond to a mesh sub-axis are not representable on ICI)."""
    global _next_gid
    gid = _next_gid
    _next_gid += 1
    if axes is None:
        axes = _world_group().axes
    g = Group(gid, axes, ranks)
    _groups[gid] = g
    return g


# -- axis scope: which mesh axes are live inside the current shard_map ----

_axis_stack = []


@contextlib.contextmanager
def axis_scope(*names):
    """Entered by parallel engines around shard_map'd bodies so eager-API
    collectives in user code resolve their mesh axis."""
    _axis_stack.append(tuple(names))
    try:
        yield
    finally:
        _axis_stack.pop()


def current_axes():
    return _axis_stack[-1] if _axis_stack else ()


def _resolve_axes(group):
    live = current_axes()
    if not live:
        return ()
    if group is None or group == 0:
        return live
    axes = group.axes if isinstance(group, Group) else tuple(group)
    return tuple(a for a in axes if a in live)


def get_axis_rank(axis):
    """Logical coordinate along `axis` (only inside a parallel region)."""
    if axis in current_axes():
        return lax.axis_index(axis)
    return 0


def _unwrap(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def _rewrap(x, val):
    if isinstance(x, Tensor):
        x.value = val
        return x
    return Tensor._from_value(val)


# -- collectives -------------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    def fn(v):
        if op == ReduceOp.SUM:
            return lax.psum(v, axes)
        if op == ReduceOp.MAX:
            return lax.pmax(v, axes)
        if op == ReduceOp.MIN:
            return lax.pmin(v, axes)
        if op == ReduceOp.AVG:
            return lax.pmean(v, axes)
        if op == ReduceOp.PROD:
            g = lax.all_gather(v, axes[0] if len(axes) == 1 else axes,
                               axis=0, tiled=False)
            return jnp.prod(g, axis=0)
        raise ValueError(f"bad ReduceOp {op}")
    out = apply(fn, tensor if isinstance(tensor, Tensor)
                else Tensor._from_value(_unwrap(tensor)),
                op_name='all_reduce')
    # reference mutates in place
    return _rewrap(tensor, out.value if isinstance(out, Tensor) else out)


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """tensor_list (out): filled with per-rank shards; returns the
    concatenated array as well (TPU-friendly return form)."""
    axes = _resolve_axes(group)
    v = _unwrap(tensor)
    if not axes:
        if isinstance(tensor_list, list):
            tensor_list.append(_rewrap(None, v) if not isinstance(tensor, Tensor)
                               else Tensor._from_value(v))
        return Tensor._from_value(v)
    name = axes[0] if len(axes) == 1 else axes
    gathered = lax.all_gather(v, name, axis=0, tiled=False)
    n = gathered.shape[0]
    if isinstance(tensor_list, list):
        for i in range(n):
            tensor_list.append(Tensor._from_value(gathered[i]))
    return Tensor._from_value(
        jnp.concatenate([gathered[i] for i in range(n)], axis=axis)
        if axis != 0 else gathered.reshape((-1,) + v.shape[1:]))


def all_gather_object(obj_list, obj, group=None):
    """Host-side object gather — single-process world: identity."""
    obj_list.append(obj)
    return obj_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    v = _unwrap(tensor)
    name = axes[0]
    idx = lax.axis_index(name)
    # select src's value, then sum (XLA lowers this to a broadcast);
    # where() not v*mask so inf/NaN on non-src ranks cannot pollute
    out = lax.psum(jnp.where(idx == src, v, jnp.zeros_like(v)), name)
    return _rewrap(tensor, out)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    v = _unwrap(tensor)
    name = axes[0]
    summed = lax.psum(v, name) if op == ReduceOp.SUM else (
        lax.pmax(v, name) if op == ReduceOp.MAX else lax.pmin(v, name))
    idx = lax.axis_index(name)
    out = jnp.where(idx == dst, summed, v)
    return _rewrap(tensor, out)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        if tensor_list:
            return _rewrap(tensor, _unwrap(tensor_list[0]))
        return tensor
    name = axes[0]
    stacked = jnp.stack([_unwrap(t) for t in tensor_list], axis=0)
    full = broadcast(Tensor._from_value(stacked), src=src, group=group)
    idx = lax.axis_index(name)
    out = lax.dynamic_index_in_dim(full.value, idx, axis=0, keepdims=False)
    return _rewrap(tensor, out)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    axes = _resolve_axes(group)
    if not axes:
        outs = [Tensor._from_value(_unwrap(t)) for t in in_tensor_list]
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(outs)
        return outs
    name = axes[0]
    x = jnp.stack([_unwrap(t) for t in in_tensor_list], axis=0)
    y = lax.all_to_all(x, name, split_axis=0, concat_axis=0, tiled=False)
    outs = [Tensor._from_value(y[i]) for i in range(y.shape[0])]
    if isinstance(out_tensor_list, list):
        out_tensor_list.extend(outs)
    return outs


def p2p(tensor, src, dst, group=None):
    """Single matched send/recv pair: rank `dst` receives rank `src`'s
    tensor; every other rank receives zeros.  lax.ppermute with one
    (src, dst) pair — the SPMD form of an NCCL send/recv pair."""
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    out = lax.ppermute(_unwrap(tensor), axes[0], [(src, dst)])
    return _rewrap(tensor, out)


def send(tensor, dst=0, group=None, sync_op=True, src=None):
    """Point-to-point send.  SPMD programs have no per-rank control
    flow, so the sender rank must be explicit: pass `src` (then this is
    p2p(src→dst)), or use p2p_rotate for the ring pattern the
    reference's pipeline engine builds out of send/recv."""
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    if src is None:
        raise ValueError(
            "send() inside an SPMD region needs src= (every rank runs "
            "this line); use p2p(tensor, src, dst) or p2p_rotate()")
    return p2p(tensor, src, dst, group)


def recv(tensor, src=0, group=None, sync_op=True, dst=None):
    """Point-to-point receive; pairs with send(). With only `src` given,
    all ranks receive src's value (a broadcast, matching how reference
    code typically consumes recv)."""
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    if dst is not None:
        return p2p(tensor, src, dst, group)
    return broadcast(tensor, src=src, group=group)


def p2p_rotate(tensor, group=None, shift=1):
    """Ring rotation: rank i → rank (i+shift)%n.  The TPU-native
    primitive behind pipeline microbatch handoff and ring attention."""
    axes = _resolve_axes(group)
    if not axes:
        return tensor
    name = axes[0]
    n = _axis_size(name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    out = lax.ppermute(_unwrap(tensor), name, perm)
    return _rewrap(tensor, out)


def _axis_size(name):
    mesh = _env.get_mesh()
    if mesh is not None and name in mesh.shape:
        return mesh.shape[name]
    return lax.psum(1, name)


def barrier(group=None):
    """XLA programs are bulk-synchronous per step; barrier is only
    meaningful host-side (multi-host sync)."""
    try:
        import jax.experimental.multihost_utils as mh
        if jax.process_count() > 1:
            mh.sync_global_devices('paddle_tpu_barrier')
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    v = _unwrap(tensor)
    if hasattr(v, 'block_until_ready'):
        v.block_until_ready()
    return tensor


def split_group(mesh_axis):
    """Convenience: the Group for one mesh axis."""
    return new_group(axes=(mesh_axis,))


# =============================================================================
# Host-side multi-process transport (the collective-layer fault surface)
# =============================================================================

KV_ENV = 'PADDLE_TPU_KV'


class CollectiveTimeout(TimeoutError):
    """A host collective's deadline expired with participants still
    missing.  Carries the op/tag and which ranks never showed — the
    watchdog and the post-mortem both need rank attribution.

    When the collective ledger is on (default) it also carries
    ``ledger_diff``: the cross-rank ring comparison at raise time.  A
    divergent diff names the first mismatched collective and its
    per-rank call sites (an SPMD contract violation — some rank issued
    a different sequence); an agreeing diff means transport loss (the
    peer recorded the same intent but its frame never arrived)."""

    def __init__(self, op, tag, missing, timeout, ledger_diff=None):
        self.op = op
        self.tag = tag
        self.missing = sorted(missing)
        self.timeout = timeout
        self.ledger_diff = ledger_diff
        msg = (f'{op}[{tag}] timed out after {timeout:.1f}s waiting '
               f'for rank(s) {self.missing}')
        if ledger_diff:
            if ledger_diff.get('agree'):
                msg += ('; collective ledgers agree — transport '
                        'loss, not a contract divergence')
            else:
                sites = ledger_diff.get('sites', {})
                per_rank = ', '.join(
                    f'r{r}={sites[r]}' for r in sorted(sites))
                msg += (f'; first ledger divergence @seq '
                        f'{ledger_diff.get("seq")} '
                        f'(op {ledger_diff.get("op")!r}, step '
                        f'{ledger_diff.get("step")}): {per_rank}')
        super().__init__(msg)


class CollectivePayloadError(ValueError):
    """A collective payload failed its frame check (crc32 / header
    mismatch).  Wire corruption must be DETECTED at the collective
    boundary, whatever the dtype — the quantized-wire path (int8/int4
    all-reduce) rides the same frame."""

    def __init__(self, op, tag, rank, detail):
        self.op = op
        self.tag = tag
        self.rank = rank
        super().__init__(
            f'{op}[{tag}] payload from rank {rank} corrupt: {detail}')


class CoordinatedAbort(RuntimeError):
    """The cluster abort flag was raised while this rank waited inside
    a collective.  Raised so the hung/waiting rank exits promptly and
    the elastic supervisor restarts the cluster from the last committed
    step, instead of every rank burning its own full timeout."""


# =============================================================================
# Collective flight recorder (the SPMD-contract runtime half)
# =============================================================================
#
# Every HostCollectives op appends (seq, op, tag, shape, dtype, step,
# call-site) to a bounded per-rank ring, and each issue republishes
# the ring over the non-blocking stats side channel (LEDGER_KEY).  On
# CollectiveTimeout / watchdog straggler / rank_divergence the probe
# diffs the rings: the first seq where two ranks that BOTH recorded an
# entry disagree on (op, tag, shape, dtype) is the first SPMD-contract
# divergence, attributed to its per-rank call sites — instead of the
# generic "rank N missing" timeout.  Recording reads only host
# metadata (never the payload values), so the ledger is sync-free and
# safe to leave on; kill switch: PADDLE_TPU_COLLECTIVE_LEDGER=0.

LEDGER_KEY = 'cledger'
LEDGER_ENV = 'PADDLE_TPU_COLLECTIVE_LEDGER'
LEDGER_DEPTH_ENV = 'PADDLE_TPU_LEDGER_DEPTH'
_LEDGER_DEPTH = 256


def ledger_enabled():
    """Collective flight recorder armed?  Default ON (ring-bounded,
    sync-free); PADDLE_TPU_COLLECTIVE_LEDGER=0 disarms."""
    return os.environ.get(LEDGER_ENV, '1').lower() not in (
        '0', 'off', 'false', 'no')


def _ledger_depth():
    try:
        return max(8, int(os.environ.get(LEDGER_DEPTH_ENV,
                                         _LEDGER_DEPTH)))
    except (TypeError, ValueError):
        return _LEDGER_DEPTH


def _call_site():
    """First stack frame outside the collective/chaos layers —
    'file.py:lineno' of the code that issued the collective."""
    skip = ('collective.py', 'chaos.py')
    fr = sys._getframe(1)
    while fr is not None and \
            os.path.basename(fr.f_code.co_filename) in skip:
        fr = fr.f_back
    if fr is None:
        return None
    return (f'{os.path.basename(fr.f_code.co_filename)}:'
            f'{fr.f_lineno}')


class CollectiveLedger:
    """Bounded per-rank ring of issued collectives with a monotone
    sequence number.  One ledger per rank per process (see
    :func:`get_ledger`) so every transport instance of a rank shares
    one seq stream — the cross-rank alignment key."""

    def __init__(self, rank, depth=None):
        self.rank = int(rank)
        self.depth = int(depth) if depth else _ledger_depth()
        self.seq = 0                # next seq to assign
        self.step = None            # trainer step, via note_step()
        self._ring = collections.deque(maxlen=self.depth)
        self._lock = threading.Lock()

    def note_step(self, step):
        """Tag subsequent entries with the trainer step (host int)."""
        try:
            self.step = int(step)
        except (TypeError, ValueError):
            pass

    def record(self, op, tag, shape=(), dtype='', site=None):
        """Append one issued collective; returns the entry."""
        entry = {'seq': None, 'op': str(op), 'tag': str(tag),
                 'shape': [int(d) for d in tuple(shape or ())],
                 'dtype': str(dtype), 'step': self.step,
                 'site': site or _call_site()}
        with self._lock:
            entry['seq'] = self.seq
            self.seq += 1
            self._ring.append(entry)
        return entry

    def entries(self):
        with self._lock:
            return [dict(e) for e in self._ring]

    def frame(self):
        """The publishable ring document (stats side channel)."""
        with self._lock:
            return {'rank': self.rank, 'seq': self.seq,
                    'depth': self.depth, 'step': self.step,
                    'entries': [dict(e) for e in self._ring]}

    def __len__(self):
        with self._lock:
            return len(self._ring)


_LEDGERS = {}
_LEDGERS_LOCK = threading.Lock()


def get_ledger(rank, depth=None):
    """The per-process singleton ledger for `rank` (trainer,
    checkpoint, and worker transports of one rank share one seq
    stream — interleaved streams would break cross-rank alignment)."""
    with _LEDGERS_LOCK:
        led = _LEDGERS.get(int(rank))
        if led is None:
            led = _LEDGERS[int(rank)] = CollectiveLedger(rank, depth)
        return led


def reset_ledgers():
    """Drop every ledger (tests; a fresh incarnation starts at seq 0)."""
    with _LEDGERS_LOCK:
        _LEDGERS.clear()


def _entry_sig(entry):
    return (entry.get('op'), entry.get('tag'),
            tuple(entry.get('shape') or ()), entry.get('dtype'))


def diff_ledgers(frames):
    """Cross-rank ring comparison -> first divergence, or agreement.

    `frames`: {rank: ledger frame doc}.  Per-rank ring window =
    [seq - len(entries), seq); seqs below a rank's window are unknown
    (ring rotated out) and skip that rank; seqs at/above its head mean
    the rank has not issued that collective yet (normal skew, not by
    itself a divergence).  The first seq where two ranks BOTH hold an
    entry and the (op, tag, shape, dtype) signatures differ is the
    first contract divergence:

        {'seq': s, 'op': ..., 'step': ...,
         'ranks': [diverging ranks], 'sites': {rank: 'file.py:line'},
         'entries': {rank: entry}}

    No such seq -> {'agree': True, 'seqs': {rank: head seq}} (rings
    consistent on their whole overlap: a stall is transport loss or
    lag, not a contract violation).  Fewer than 2 readable frames ->
    None (nothing to compare)."""
    rings = {}
    for rank, doc in (frames or {}).items():
        if not isinstance(doc, dict):
            continue
        entries = doc.get('entries') or []
        try:
            head = int(doc.get('seq', len(entries)))
        except (TypeError, ValueError):
            continue
        start = head - len(entries)
        rings[int(rank)] = (start, head, entries)
    if len(rings) < 2:
        return None
    lo = min(start for start, _, _ in rings.values())
    hi = max(head for _, head, _ in rings.values())
    for s in range(max(0, lo), hi):
        present = {}
        for rank, (start, head, entries) in rings.items():
            if start <= s < head:
                present[rank] = entries[s - start]
        if len(present) < 2:
            continue
        sigs = {rank: _entry_sig(e) for rank, e in present.items()}
        if len(set(sigs.values())) > 1:
            ranks = sorted(present)
            first = present[ranks[0]]
            return {
                'seq': s,
                'op': first.get('op'),
                'step': first.get('step'),
                'ranks': ranks,
                'sites': {r: present[r].get('site') for r in ranks},
                'entries': {r: present[r] for r in ranks},
            }
    return {'agree': True,
            'seqs': {r: head for r, (_, head, _) in rings.items()}}


def probe_mismatch(transport, trigger, emit=True):
    """Diff this rank's live ledger against every peer's published
    ring frame; on a definite divergence emit ``collective_mismatch``
    naming the first mismatched entry and per-rank call sites.
    Returns the diff (or None).  Never raises, never blocks — safe
    from the watchdog thread and from inside an exception path."""
    try:
        led = get_ledger(transport.rank)
        frames = dict(transport.read_all_stats(key=LEDGER_KEY))
        frames[transport.rank] = led.frame()
        diff = diff_ledgers(frames)
        if diff and not diff.get('agree') and emit:
            from .. import telemetry
            telemetry.event(
                'collective_mismatch', trigger=str(trigger),
                seq=diff['seq'], op=diff['op'], step=diff['step'],
                ranks=diff['ranks'],
                sites={str(r): s for r, s in diff['sites'].items()},
                rank=transport.rank)
        return diff
    except Exception:
        return None


class FileKVStore:
    """A restart-proof key-value store over a shared directory.

    Same interface subset as jax's DistributedRuntimeClient
    (``key_value_set_bytes`` / ``blocking_key_value_get_bytes`` / ...),
    but backed by atomic files: a worker that was SIGKILLed can respawn
    and keep participating, which the coordination service does not
    allow (a dead task cannot re-register).  This is the transport the
    multi-process chaos topology runs on; real pods use the jax client.

    Writes go through resilience.manifest.atomic_write, so the file
    seam's torn-write/EIO chaos faults apply to the collective wire
    exactly as they do to checkpoints."""

    def __init__(self, directory, poll=0.005):
        self.directory = os.path.abspath(directory)
        self.poll = poll
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, key):
        # keys may contain '/'; quote to one flat filename so listing
        # and deletion stay trivial
        from urllib.parse import quote
        return os.path.join(self.directory, quote(str(key), safe=''))

    def key_value_set_bytes(self, key, value):
        from ..resilience.manifest import atomic_write
        atomic_write(self._path(key), lambda f: f.write(value),
                     mode='wb', prefix='.kv_tmp')

    def key_value_set(self, key, value):
        self.key_value_set_bytes(key, value.encode('utf-8'))

    def try_get_bytes(self, key):
        try:
            with open(self._path(key), 'rb') as f:
                return f.read()
        except OSError:
            return None

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            v = self.try_get_bytes(key)
            if v is not None:
                return v
            if time.monotonic() >= deadline:
                raise TimeoutError(f'key {key!r} not set within '
                                   f'{timeout_ms}ms')
            time.sleep(self.poll)

    def blocking_key_value_get(self, key, timeout_ms):
        return self.blocking_key_value_get_bytes(
            key, timeout_ms).decode('utf-8')

    def key_value_delete(self, key):
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def key_value_dir_get_bytes(self, prefix):
        from urllib.parse import quote, unquote
        q = quote(str(prefix), safe='')
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for f in sorted(names):
            if not f.startswith(q) or f.startswith('.'):
                continue
            v = self.try_get_bytes(unquote(f))
            if v is not None:
                out.append((unquote(f), v))
        return out


_kv_client = None


def set_kv_client(client):
    """Install the process-global KV client (tests, chaos workers).
    Pass None to fall back to env/jax discovery."""
    global _kv_client
    _kv_client = client
    return client


def get_kv_client():
    """The host-transport KV client, resolved once per process:
    an explicitly installed client wins; then ``PADDLE_TPU_KV``
    (``file:<dir>`` — the chaos topology ships this); then a live
    ``jax.distributed`` coordination-service client; else None
    (single-process world: HostCollectives degrades to identity)."""
    global _kv_client
    if _kv_client is not None:
        return _kv_client
    spec = os.environ.get(KV_ENV)
    if spec:
        if spec.startswith('file:'):
            _kv_client = FileKVStore(spec[len('file:'):])
            return _kv_client
        raise ValueError(f'unsupported {KV_ENV} spec {spec!r} '
                         "(expected 'file:<dir>')")
    try:
        from jax._src import distributed as _jd
        client = getattr(_jd.global_state, 'client', None)
        if client is not None:
            _kv_client = client
            return _kv_client
    except Exception:
        pass
    return None


def _frame(arr, extra=None):
    """Serialize one ndarray with an explicit header: dtype, shape and
    a crc32 of the raw bytes.  Dtype-agnostic on purpose — int8/uint8
    (quantized wire traffic) frames identically to f32, and the
    receiver verifies the crc BEFORE interpreting a single element.
    ``extra`` header fields (the quantized wire's block metadata) ride
    the SAME frame, covered by the same crc discipline."""
    a = np.ascontiguousarray(arr)
    raw = a.tobytes()
    doc = {'dtype': a.dtype.str, 'shape': list(a.shape),
           'crc32': binascii.crc32(raw) & 0xFFFFFFFF,
           'nbytes': len(raw)}
    if extra:
        doc.update(extra)
    head = json.dumps(doc).encode('utf-8')
    return len(head).to_bytes(4, 'big') + head + raw


# -- block-scaled int8 host wire (the numpy twin of ---------------------------
#    parallel.quant_collectives' device core; deterministic rounding —
#    host payloads must replay bit-identically across elastic restarts)

WIRE_QUANT_BLOCK = 256


def _quantize_host(arr, block=WIRE_QUANT_BLOCK):
    """float ndarray -> (int8 [nb, block], f32 scales [nb]); per-block
    symmetric abs-max, round-half-even (np.rint) — pure in the input,
    so a restarted rank re-posting the same step re-frames the
    identical bytes."""
    flat = np.ascontiguousarray(arr).reshape(-1).astype(np.float32)
    pad = (-flat.size) % block
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    xb = flat.reshape(-1, block)
    scales = np.maximum(np.abs(xb).max(axis=1) / 127.0,
                        np.float32(1e-30)).astype(np.float32)
    q = np.clip(np.rint(xb / scales[:, None]), -127,
                127).astype(np.int8)
    return q, scales


def _frame_quant(arr, block=WIRE_QUANT_BLOCK):
    """Frame a float array as int8 blocks + f32 scales in ONE crc32-
    covered payload: [scales f32 | q int8], with the block layout and
    the original dtype/shape in the header.  A byte flipped anywhere
    after the header — scales or body — fails the crc."""
    a = np.ascontiguousarray(arr)
    q, scales = _quantize_host(a, block)
    packed = np.concatenate([scales.view(np.uint8).reshape(-1),
                             q.view(np.uint8).reshape(-1)])
    return _frame(packed, extra={
        'wire': 'int8-block', 'block': int(block),
        'nscales': int(scales.size),
        'orig_dtype': a.dtype.str, 'orig_shape': list(a.shape)})


def _unframe(payload, op, tag, rank):
    if len(payload) < 4:
        raise CollectivePayloadError(op, tag, rank, 'frame truncated')
    hlen = int.from_bytes(payload[:4], 'big')
    try:
        head = json.loads(payload[4:4 + hlen].decode('utf-8'))
    except (ValueError, UnicodeDecodeError) as e:
        raise CollectivePayloadError(op, tag, rank,
                                     f'header unparseable ({e})')
    raw = payload[4 + hlen:]
    if len(raw) != head.get('nbytes'):
        raise CollectivePayloadError(
            op, tag, rank,
            f'{len(raw)} payload bytes != recorded {head.get("nbytes")}')
    crc = binascii.crc32(raw) & 0xFFFFFFFF
    if crc != head.get('crc32'):
        raise CollectivePayloadError(
            op, tag, rank, f'crc32 {crc:#x} != recorded '
            f'{head.get("crc32"):#x}')
    arr = np.frombuffer(raw, dtype=np.dtype(head['dtype'])).reshape(
        head['shape']).copy()
    if head.get('wire') == 'int8-block':
        # dequantize AFTER the crc held: scales then body
        ns = int(head['nscales'])
        block = int(head['block'])
        body = arr[ns * 4:]
        if body.size != ns * block:
            raise CollectivePayloadError(
                op, tag, rank, f'{body.size} quant payload bytes != '
                f'{ns} blocks x {block}')
        scales = arr[:ns * 4].view(np.float32)
        q = body.view(np.int8).reshape(ns, block)
        flat = (q.astype(np.float32) * scales[:, None]).reshape(-1)
        shape = tuple(head['orig_shape'])
        n = int(np.prod(shape)) if shape else 1
        return flat[:n].reshape(shape).astype(
            np.dtype(head['orig_dtype']))
    return arr


class HostCollectives:
    """Host-side collectives across real process boundaries.

    Each rank posts its framed payload under a deterministic key
    ``<ns>/<tag>/<op>/r<rank>`` and blockingly fetches every peer's.
    Keys are tagged by the CALLER (typically with the step id), which
    makes the exchange replay-stable across elastic restarts: a
    restarted rank that restored an older committed step re-fetches its
    peers' already-posted step keys and catches up, while the peers
    wait at the barrier of the step the straggler has not reached yet.
    (The contract: per-step payloads must be deterministic functions of
    the step — true for SPMD training state.)

    Every wait is bounded by ``timeout_s`` and polls the cluster abort
    flag; on deadline the raiser names the missing ranks
    (CollectiveTimeout) so the watchdog can attribute the straggler.
    Old generations are pruned lazily (``gc_window`` step-tags deep).
    """

    ABORT_KEY = 'abort'

    def __init__(self, client=None, rank=None, world=None,
                 namespace='ptpu', timeout_s=60.0, poll=0.01,
                 gc_window=32, quant=None, quant_min_bytes=1024):
        # quant: 'int8' (or True) ships float payloads as block-scaled
        # int8 + f32 scales inside the same crc frame (EQuARX host
        # wire).  Instance default; per-call ``quant=`` overrides.
        # Arrays below quant_min_bytes ship full width (scale overhead
        # wins).  ALL ranks must agree on the setting: the sum runs
        # over every rank's DEQUANTIZED payload — own contribution
        # included — so results stay bitwise identical cluster-wide.
        self.client = client if client is not None else get_kv_client()
        if rank is None:
            rank = int(os.environ.get('PADDLE_TRAINER_ID', 0) or 0)
        if world is None:
            world = os.environ.get('PADDLE_TRAINERS_NUM')
            if world is None:
                try:
                    world = jax.process_count()
                except RuntimeError:
                    world = 1
        self.rank = int(rank)
        self.world = int(world)
        self.namespace = namespace
        self.timeout_s = float(timeout_s)
        self.poll = poll
        self.gc_window = gc_window
        self.quant = quant
        self.quant_min_bytes = int(quant_min_bytes)
        self._history = []          # posted (tag, op) for lazy gc
        self._epoch = time.time()   # aborts older than our start are
                                    # a previous incarnation's
        # collective flight recorder: per-rank singleton so every
        # transport of this rank shares one seq stream
        self._ledger = get_ledger(self.rank) if ledger_enabled() \
            else None

    def note_step(self, step):
        """Tag subsequent ledger entries with the trainer step."""
        if self._ledger is not None:
            self._ledger.note_step(step)

    def ledger_frame(self):
        """This rank's live ring document, or None (ledger off)."""
        return None if self._ledger is None else self._ledger.frame()

    # -- keys / abort flag ---------------------------------------------------

    def _key(self, tag, op, rank):
        return f'{self.namespace}/{tag}/{op}/r{rank}'

    def _abort_key(self):
        return f'{self.namespace}/{self.ABORT_KEY}'

    def request_abort(self, reason=''):
        """Raise the cluster abort flag: every rank polling inside a
        collective observes it within one poll interval and raises
        CoordinatedAbort instead of waiting out its own timeout."""
        if self.client is None:
            return
        doc = json.dumps({'ts': time.time(), 'rank': self.rank,
                          'reason': str(reason)[:200]})
        try:
            self.client.key_value_set_bytes(self._abort_key(),
                                            doc.encode('utf-8'))
        except Exception:
            pass

    def clear_abort(self):
        """Called at worker startup: a NEW incarnation must not be
        killed by the abort that restarted it."""
        if self.client is None:
            return
        try:
            self.client.key_value_delete(self._abort_key())
        except Exception:
            pass

    def try_get(self, key):
        """Non-blocking-ish read of one key on ANY client:
        FileKVStore's try_get_bytes when present, else a 1ms blocking
        get on the jax coordination-service client (absence reads as
        None).  The abort flag and the watchdog's peer heartbeats go
        through this so they work on real pods, not just the file
        store."""
        c = self.client
        if c is None:
            return None
        if hasattr(c, 'try_get_bytes'):
            return c.try_get_bytes(key)
        try:
            return c.blocking_key_value_get_bytes(key, 1)
        except Exception:
            return None

    def abort_requested(self):
        """The live abort doc, or None.  Aborts raised before this
        transport's creation are stale (previous incarnation) and are
        ignored — clear_abort races with slow starters otherwise."""
        raw = self.try_get(self._abort_key())
        if raw is None:
            return None
        try:
            doc = json.loads(raw.decode('utf-8'))
        except (ValueError, UnicodeDecodeError):
            return None
        if doc.get('ts', 0) < self._epoch:
            return None
        return doc

    # -- transport primitives (the chaos seam patches these) -----------------

    def post(self, tag, op, payload):
        """Publish this rank's framed payload for one collective."""
        self.client.key_value_set_bytes(
            self._key(tag, op, self.rank), payload)
        self._history.append((tag, op))
        self._gc()

    def fetch(self, tag, op, rank, deadline):
        """Blocking fetch of `rank`'s payload, bounded by `deadline`
        (monotonic), polling the abort flag between attempts."""
        poll_ms = max(1, int(self.poll * 1000))
        while True:
            try:
                return self.client.blocking_key_value_get_bytes(
                    self._key(tag, op, rank), poll_ms)
            except Exception:
                pass
            doc = self.abort_requested()
            if doc is not None:
                raise CoordinatedAbort(
                    f'{op}[{tag}]: abort requested by rank '
                    f'{doc.get("rank")} ({doc.get("reason")!r})')
            if time.monotonic() >= deadline:
                return None

    def _gc(self):
        """Prune own keys older than gc_window collectives — bounded
        disk/KV growth without breaking replay (a restarted rank can
        lag at most the checkpoint cadence, which the caller keeps
        well inside the window)."""
        while len(self._history) > self.gc_window:
            tag, op = self._history.pop(0)
            try:
                self.client.key_value_delete(
                    self._key(tag, op, self.rank))
            except Exception:
                pass

    # -- collectives ---------------------------------------------------------

    def _effective_timeout(self, timeout_s):
        """The wait bound for one collective: the explicit/default
        timeout, clamped by a started Watchdog's per-collective budget
        (``Budget.collective_s``) and by any enclosing
        ``collective_budget`` scope."""
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        try:
            from ..resilience.watchdog import (
                remaining_budget, default_collective_s)
            dflt = default_collective_s()
            if dflt is not None:
                t = min(t, float(dflt))
            rem = remaining_budget()
            if rem is not None:
                t = min(t, max(0.01, rem))
        except Exception:
            pass
        return t

    def _use_quant(self, arr, quant):
        """True when this payload should ride the int8 block wire:
        an armed quant setting, a float array, and enough bytes that
        the per-block scales do not eat the savings."""
        q = self.quant if quant is None else quant
        if not q or q in ('0', 'off', 'none', False):
            return False
        if q not in ('int8', True, '1'):
            raise ValueError(f'host quant wire {q!r}: only int8')
        a = np.asarray(arr)
        return (np.issubdtype(a.dtype, np.floating)
                and a.nbytes >= self.quant_min_bytes)

    def _exchange(self, tag, op, arr, timeout_s=None, quant=None):
        """Post own frame, fetch every peer's; returns {rank: ndarray}.
        The whole exchange runs inside a collective_budget scope of
        its effective timeout, so nested bounded waits — retry() on a
        flaky shared fs, most of all — cannot outlive it.  Under the
        quantized wire the OWN contribution also round-trips through
        its frame: every rank reduces over identical dequantized
        values, keeping results bitwise equal across the cluster."""
        if self._ledger is not None:
            # host metadata only (shape/dtype attrs, never values) —
            # recording is sync-free even for device arrays
            self._ledger.record(
                op, tag, getattr(arr, 'shape', ()) or (),
                getattr(arr, 'dtype', type(arr).__name__))
        if self.client is None or self.world <= 1:
            return {self.rank: np.asarray(arr)}
        t = self._effective_timeout(timeout_s)
        try:
            from ..resilience.watchdog import collective_budget
            scope = collective_budget(t)
        except Exception:       # pragma: no cover - defensive
            scope = contextlib.nullcontext()
        with scope:
            quantized = self._use_quant(arr, quant)
            own = _frame_quant(np.asarray(arr)) if quantized \
                else _frame(np.asarray(arr))
            self.post(tag, op, own)
            if self._ledger is not None:
                # republish the ring on the non-blocking stats
                # channel BEFORE waiting: peers can diff against our
                # intent even while we hang
                self.post_stats(self._ledger.frame(), key=LEDGER_KEY)
            deadline = time.monotonic() + t
            out, missing = {}, []
            for r in range(self.world):
                if r == self.rank:
                    # quantized: the OWN contribution round-trips
                    # through its frame so every rank reduces over
                    # identical dequantized values; full width keeps
                    # the old zero-copy path (no redundant crc)
                    out[r] = _unframe(own, op, tag, r) if quantized \
                        else np.asarray(arr)
                    continue
                payload = self.fetch(tag, op, r, deadline)
                if payload is None:
                    missing.append(r)
                    continue
                out[r] = _unframe(payload, op, tag, r)
        if missing:
            # ledger diff FIRST: a divergence emits the attributed
            # collective_mismatch before the generic timeout event
            diff = probe_mismatch(self, trigger='timeout') \
                if self._ledger is not None else None
            self._note_timeout(op, tag, missing, t)
            raise CollectiveTimeout(op, tag, missing, t,
                                    ledger_diff=diff)
        return out

    def _note_timeout(self, op, tag, missing, timeout):
        try:
            from .. import telemetry
            telemetry.event('timeout', op=op, tag=tag,
                            missing=sorted(missing),
                            budget_s=round(timeout, 3), rank=self.rank)
            telemetry.add('collective.timeouts')
        except Exception:
            pass

    # -- stats-frame side channel (telemetry.cluster) ------------------------
    #
    # A NON-BLOCKING publish/read channel riding the same KV transport
    # the collectives use: each rank overwrites ONE well-known key
    # (``<ns>/cstats/r<rank>``) with a small JSON document, and any
    # rank reads every peer's latest frame without waiting.  No
    # barrier, no deadline, no device sync — a dead peer simply stops
    # refreshing its key and its frame goes stale, which is exactly
    # the degraded-view semantics the cluster observability plane
    # wants (a crashed rank must never crash the observer).

    STATS_KEY = 'cstats'

    def post_stats(self, doc, key=None):
        """Overwrite this rank's stats frame.  Never blocks, never
        raises — publishing telemetry must not be able to kill (or
        stall) a training step.  Returns True when the write landed."""
        if self.client is None:
            return False
        k = f'{self.namespace}/{key or self.STATS_KEY}/r{self.rank}'
        try:
            payload = json.dumps(doc, default=str).encode('utf-8')
        except (TypeError, ValueError):
            return False
        try:
            self.client.key_value_set_bytes(k, payload)
            return True
        except Exception:
            # some KV backends (jax coordination service) reject
            # overwrites: best-effort delete-then-set, then give up
            try:
                self.client.key_value_delete(k)
                self.client.key_value_set_bytes(k, payload)
                return True
            except Exception:
                return False

    def read_stats(self, rank, key=None):
        """`rank`'s latest stats frame as a dict, or None (absent,
        unreadable, or corrupt — a torn frame is skipped, not an
        error)."""
        raw = self.try_get(
            f'{self.namespace}/{key or self.STATS_KEY}/r{rank}')
        if raw is None:
            return None
        try:
            doc = json.loads(raw.decode('utf-8'))
        except (ValueError, UnicodeDecodeError):
            return None
        return doc if isinstance(doc, dict) else None

    def read_all_stats(self, key=None):
        """{rank: frame} for every rank with a readable frame.  Purely
        non-blocking: missing ranks are simply absent."""
        out = {}
        for r in range(self.world):
            doc = self.read_stats(r, key=key)
            if doc is not None:
                out[r] = doc
        return out

    def read_heartbeats(self):
        """{rank: age_s} for every rank with a readable watchdog
        heartbeat (the ``hb/r<rank>`` keys resilience.watchdog
        publishes) — own rank included."""
        out = {}
        now = time.time()
        for r in range(self.world):
            raw = self.try_get(f'{self.namespace}/hb/r{r}')
            if raw is None:
                continue
            try:
                out[r] = now - json.loads(raw.decode('utf-8'))['ts']
            except (ValueError, KeyError, UnicodeDecodeError):
                continue
        return out

    def allreduce(self, arr, op='sum', tag='ar', timeout_s=None,
                  quant=None):
        """Cross-process all-reduce of one host array (any dtype).
        op: 'sum' | 'mean' | 'max' | 'min'.  ``quant='int8'`` ships
        the payload as block-scaled int8 (scales inside the crc
        frame); the reduction itself runs full width over the
        dequantized parts."""
        parts = self._exchange(tag, f'allreduce-{op}', arr,
                               timeout_s=timeout_s, quant=quant)
        stack = np.stack([parts[r] for r in sorted(parts)])
        if op == 'sum':
            return stack.sum(axis=0).astype(stack.dtype)
        if op == 'mean':
            return stack.mean(axis=0).astype(stack.dtype)
        if op == 'max':
            return stack.max(axis=0)
        if op == 'min':
            return stack.min(axis=0)
        raise ValueError(f'bad host allreduce op {op!r}')

    def allgather(self, arr, tag='ag', timeout_s=None):
        """[world, ...] stack of every rank's EXACT array.  Always
        full width — gathers exchange state whose bitwise identity
        matters (digests, reference weights), so the instance quant
        default deliberately does not apply; only the lossy-by-
        construction :meth:`allreduce` consults it."""
        parts = self._exchange(tag, 'allgather', arr,
                               timeout_s=timeout_s, quant=False)
        return np.stack([parts[r] for r in sorted(parts)])

    def allgather_object(self, obj, tag='ago', timeout_s=None):
        """Every rank's python object, as a rank-ordered list (pickle
        payloads ride the same crc-framed wire)."""
        buf = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        parts = self._exchange(tag, 'allgather_object', buf,
                               timeout_s=timeout_s)
        return [pickle.loads(parts[r].tobytes())
                for r in sorted(parts)]

    def broadcast_object(self, obj, src=0, tag='bc', timeout_s=None):
        """src's object on every rank."""
        op = 'broadcast'
        if self._ledger is not None:
            # both roles (post and fetch) record the SAME logical
            # entry — a broadcast is one collective, not two
            self._ledger.record(op, tag, (), 'object')
        if self.client is None or self.world <= 1:
            return obj
        t = self._effective_timeout(timeout_s)
        if self.rank == src:
            buf = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
            self.post(tag, op, _frame(buf))
            if self._ledger is not None:
                self.post_stats(self._ledger.frame(), key=LEDGER_KEY)
            return obj
        if self._ledger is not None:
            self.post_stats(self._ledger.frame(), key=LEDGER_KEY)
        payload = self.fetch(tag, op, src, time.monotonic() + t)
        if payload is None:
            diff = probe_mismatch(self, trigger='timeout') \
                if self._ledger is not None else None
            self._note_timeout(op, tag, [src], t)
            raise CollectiveTimeout(op, tag, [src], t,
                                    ledger_diff=diff)
        return pickle.loads(_unframe(payload, op, tag,
                                     src).tobytes())

    def barrier_host(self, tag='bar', timeout_s=None):
        """All ranks reach this tag (a 1-byte allgather)."""
        self._exchange(tag, 'barrier',
                       np.zeros((1,), np.uint8), timeout_s=timeout_s)
        return True
