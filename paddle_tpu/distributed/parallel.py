"""init_parallel_env + DataParallel.

Reference analogue: /root/reference/python/paddle/distributed/parallel.py.
The reference's dygraph DataParallel registers grad hooks that issue
NCCL allreduce per bucket.  TPU-native DataParallel instead *shards the
batch over the dp mesh axis* and lets XLA insert the gradient
reduce-scatter/all-reduce:

  * eager (1 process): DataParallel is transparent — forward unchanged;
    `apply_collective_grads` psum-averages grads ONLY inside a parallel
    region (shard_map).  Single chip: identity.
  * compiled (fleet engine / hapi): the train step is shard_mapped over
    the mesh with batch sharded on 'dp'; grads come out of jax.grad
    already per-shard, one `psum` over 'dp' synchronizes — exactly the
    reference's allreduce semantics but fused by XLA.
"""
import numpy as np

from ..nn.layer.layers import Layer
from . import env as _env
from . import collective

__all__ = ['init_parallel_env', 'DataParallel']


def init_parallel_env(n_devices=None, axes=None):
    """Build and install the global mesh.

    Reference signature takes no args (env vars decide); here optional
    `axes` (e.g. {'dp': 2, 'tp': 4}) controls topology — default is a
    pure data-parallel mesh over all visible chips.
    """
    import jax
    if _env.get_mesh() is not None and n_devices is None and axes is None:
        return _env.ParallelEnv()
    if axes is None:
        n = n_devices or jax.device_count()
        axes = {'dp': n}
    mesh = _env.build_mesh(axes)
    _env.set_mesh(mesh)
    return _env.ParallelEnv()


class DataParallel(Layer):
    """Reference: python/paddle/fluid/dygraph/parallel.py::DataParallel."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # XLA psum-of-mean over equal shards already averages; keep the
        # reference's API (it divides by nranks before backward).
        axes = collective.current_axes()
        if not axes or 'dp' not in axes:
            return loss
        n = _env.get_mesh().shape.get('dp', 1) if _env.get_mesh() else 1
        return loss / float(n)

    def apply_collective_grads(self):
        """psum gradients over the dp axis (no-op outside a parallel
        region — single chip or already-synchronized compiled step)."""
        axes = collective.current_axes()
        if not axes or 'dp' not in axes:
            return
        import jax.lax as lax
        for p in self._layers.parameters():
            if p._grad is not None:
                p._grad = lax.psum(p._grad, 'dp')

    # delegate state management to the wrapped layer
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix='', include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
