"""python -m paddle_tpu.distributed.launch — multi-host entry point.

Reference analogue: /root/reference/python/paddle/distributed/launch
(fleet launch_utils spawn one worker per GPU and wire NCCL env vars).

TPU-native: ONE process per host drives all its local chips; the hosts
rendezvous through jax.distributed (GRPC coordination service), after
which jax.devices() is the GLOBAL device list and every collective in
this package rides ICI/DCN via GSPMD.  On a TPU pod slice the runtime
publishes the coordinator automatically, so

    python -m paddle_tpu.distributed.launch train.py --lr 0.1

on every host is all that is needed (same command, every host).  Off-pod
(CPU/GPU clusters) pass the rendezvous explicitly:

    python -m paddle_tpu.distributed.launch \
        --coordinator 10.0.0.1:1234 --nnodes 4 --node-rank $I train.py
"""
import argparse
import os
import runpy
import sys

__all__ = ['launch_main']


def launch_main(argv=None):
    ap = argparse.ArgumentParser(
        prog='paddle_tpu.distributed.launch',
        description='Run a training script with jax.distributed '
                    'initialized (one process per host).')
    ap.add_argument('--coordinator', default=None,
                    help='coordinator host:port (omit on TPU pods — the '
                         'runtime auto-detects)')
    ap.add_argument('--nnodes', type=int, default=None,
                    help='total number of host processes')
    ap.add_argument('--node-rank', type=int, default=None,
                    help='this host\'s index in [0, nnodes)')
    ap.add_argument('script', help='training script to run')
    ap.add_argument('script_args', nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    import jax
    explicit = args.coordinator is not None
    if explicit:
        if args.nnodes is None or args.node_rank is None:
            ap.error('--coordinator requires --nnodes and --node-rank')
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.nnodes,
            process_id=args.node_rank)
    else:
        # TPU pod: the runtime supplies coordinator/count/id; single-host
        # runs (tests, 1 chip, or pod env vars present but stale) fall
        # through — only the explicit --coordinator path raises hard
        if os.environ.get('TPU_WORKER_HOSTNAMES') or \
                os.environ.get('MEGASCALE_COORDINATOR_ADDRESS'):
            try:
                jax.distributed.initialize()
            except Exception as e:
                import warnings
                warnings.warn(
                    f'jax.distributed auto-initialize failed ({e}); '
                    'continuing single-host — pass --coordinator/'
                    '--nnodes/--node-rank for an explicit rendezvous')

    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name='__main__')


if __name__ == '__main__':
    launch_main()
