"""python -m paddle_tpu.distributed.launch — multi-host entry point.

Reference analogue: /root/reference/python/paddle/distributed/launch
(fleet launch_utils spawn one worker per GPU and wire NCCL env vars).

TPU-native: ONE process per host drives all its local chips; the hosts
rendezvous through jax.distributed (GRPC coordination service), after
which jax.devices() is the GLOBAL device list and every collective in
this package rides ICI/DCN via GSPMD.  On a TPU pod slice the runtime
publishes the coordinator automatically, so

    python -m paddle_tpu.distributed.launch train.py --lr 0.1

on every host is all that is needed (same command, every host).  Off-pod
(CPU/GPU clusters) pass the rendezvous explicitly:

    python -m paddle_tpu.distributed.launch \
        --coordinator 10.0.0.1:1234 --nnodes 4 --node-rank $I train.py
"""
import argparse
import os
import runpy
import sys

__all__ = ['launch_main']


def launch_main(argv=None):
    ap = argparse.ArgumentParser(
        prog='paddle_tpu.distributed.launch',
        description='Run a training script with jax.distributed '
                    'initialized (one process per host).')
    ap.add_argument('--coordinator', default=None,
                    help='coordinator host:port (omit on TPU pods — the '
                         'runtime auto-detects)')
    ap.add_argument('--nnodes', type=int, default=None,
                    help='total number of host processes')
    ap.add_argument('--node-rank', type=int, default=None,
                    help='this host\'s index in [0, nnodes)')
    ap.add_argument('--elastic', type=int, default=None,
                    metavar='MAX_RESTARTS',
                    help='supervise the worker: restart it up to '
                         'MAX_RESTARTS times on failure (reference '
                         'launch_utils pod watch); pair with incubate.'
                         'checkpoint.auto_checkpoint so the restarted '
                         'worker resumes from the last snapshot')
    ap.add_argument('--elastic-log-dir', default=None,
                    help='worker log dir in elastic mode')
    ap.add_argument('--heartbeat-file', default=None,
                    help='worker heartbeat file; a stale mtime beyond '
                         '--heartbeat-timeout restarts the worker')
    ap.add_argument('--heartbeat-timeout', type=float, default=None)
    ap.add_argument('script', help='training script to run')
    ap.add_argument('script_args', nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    # usage errors must fail HERE, not burn the elastic restart budget
    # on a worker that exits 2 every incarnation
    if args.coordinator is not None and (args.nnodes is None
                                         or args.node_rank is None):
        ap.error('--coordinator requires --nnodes and --node-rank')
    if (args.heartbeat_file is None) != (args.heartbeat_timeout is None):
        ap.error('--heartbeat-file and --heartbeat-timeout must be '
                 'passed together')

    if args.elastic is not None:
        # per-host supervision: re-exec this launcher WITHOUT --elastic
        # as the worker, watch it, restart on failure
        from .elastic import supervise
        cmd = [sys.executable, '-u', '-m',
               'paddle_tpu.distributed.launch']
        if args.coordinator is not None:
            cmd += ['--coordinator', args.coordinator,
                    '--nnodes', str(args.nnodes),
                    '--node-rank', str(args.node_rank)]
        cmd += [args.script] + args.script_args
        if args.heartbeat_file is not None:
            # the worker must KNOW the heartbeat path or it can never
            # touch it and the supervisor would kill a healthy worker
            # every heartbeat_timeout; auto_checkpoint reads this env
            # var when no explicit heartbeat_file is configured
            os.environ['PADDLE_TPU_HEARTBEAT_FILE'] = \
                args.heartbeat_file
        rc = supervise(cmd, max_restarts=args.elastic,
                       log_dir=args.elastic_log_dir,
                       heartbeat_file=args.heartbeat_file,
                       heartbeat_timeout=args.heartbeat_timeout)
        sys.exit(rc)

    import jax
    explicit = args.coordinator is not None
    if explicit:
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.nnodes,
            process_id=args.node_rank)
    else:
        # TPU pod: the runtime supplies coordinator/count/id; single-host
        # runs (tests, 1 chip, or pod env vars present but stale) fall
        # through — only the explicit --coordinator path raises hard
        if os.environ.get('TPU_WORKER_HOSTNAMES') or \
                os.environ.get('MEGASCALE_COORDINATOR_ADDRESS'):
            try:
                jax.distributed.initialize()
            except Exception as e:
                import warnings
                warnings.warn(
                    f'jax.distributed auto-initialize failed ({e}); '
                    'continuing single-host — pass --coordinator/'
                    '--nnodes/--node-rank for an explicit rendezvous')

    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name='__main__')


if __name__ == '__main__':
    launch_main()
