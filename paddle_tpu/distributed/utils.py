"""Launch topology utilities (`paddle.distributed.utils`).

Reference: /root/reference/python/paddle/distributed/utils.py (Cluster/
Pod/Trainer containers, get_cluster, start/watch/terminate local
trainers).  TPU-native adaptation: a "device" is a TPU chip index, the
per-trainer env pins `TPU_VISIBLE_DEVICES` (the reference pins
`FLAGS_selected_gpus`), and process supervision is shared with the
elastic launcher (`distributed/elastic.py`) instead of a bespoke loop.
The rendezvous fabric is jax.distributed — endpoints here exist for
API compatibility and env wiring, not for an RPC mesh of our own.
"""
import logging
import os
import socket
import subprocess
import sys

from . import elastic as _elastic

__all__ = [
    'get_host_name_ip', 'Trainer', 'get_cluster', 'start_local_trainers',
    'watch_local_trainers', 'find_free_ports', 'JobServer', 'Cluster',
    'Pod', 'Hdfs', 'add_arguments', 'terminate_local_procs',
    'TrainerProc', 'get_logger', 'pull_worker_log',
]

logger = logging.getLogger('paddle_tpu.distributed')


def get_logger(log_level=20, name='root'):
    """Reference utils.py:303 — module logger with a stream handler."""
    lg = logging.getLogger(name)
    lg.setLevel(log_level)
    if not lg.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            '%(asctime)s-%(levelname)s: %(message)s'))
        lg.addHandler(h)
    return lg


class Hdfs:
    """Checkpoint-store coordinates (reference utils.py:117).  Kept as
    a plain record; actual HDFS IO is a documented non-goal (SURVEY) —
    checkpoints go through orbax/local paths."""

    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return None not in (self.hdfs_ugi, self.hdfs_name, self.hdfs_path)

    def __eq__(self, o):
        return (self.hdfs_ugi, self.hdfs_name, self.hdfs_path) == \
            (o.hdfs_ugi, o.hdfs_name, o.hdfs_path)

    def __ne__(self, o):
        return not self == o

    def __str__(self):
        return (f'hdfs_ugi:{self.hdfs_ugi} hdfs_name:{self.hdfs_name} '
                f'hdfs_path:{self.hdfs_path}')


class JobServer:
    def __init__(self):
        self.endpoint = None

    def __eq__(self, o):
        return self.endpoint == o.endpoint

    def __ne__(self, o):
        return not self == o

    def __str__(self):
        return str(self.endpoint)


class Trainer:
    """One worker process: its devices (TPU chip indices), rendezvous
    endpoint, and global rank."""

    def __init__(self):
        self.accelerators = []
        self.endpoint = None
        self.rank = None

    # the reference field is `gpus`; keep it as an alias so legacy
    # launch scripts that poke trainer.gpus keep working
    @property
    def gpus(self):
        return self.accelerators

    def __eq__(self, t):
        return (self.accelerators == t.accelerators
                and self.endpoint == t.endpoint and self.rank == t.rank)

    def __ne__(self, t):
        return not self == t

    def __str__(self):
        return (f'accelerators:{self.accelerators} '
                f'endpoint:{self.endpoint} rank:{self.rank}')


class Pod:
    """One host: its address, port, and resident trainers."""

    def __init__(self):
        self.rank = None
        self.id = None
        self.addr = None
        self.port = None
        self.trainers = []
        self.accelerators = []

    @property
    def gpus(self):
        return self.accelerators

    def __eq__(self, pod):
        return (self.rank == pod.rank and self.id == pod.id
                and self.addr == pod.addr and self.port == pod.port
                and self.trainers == pod.trainers)

    def __ne__(self, pod):
        return not self == pod

    def get_visible_accelerators(self):
        if not self.accelerators:
            raise ValueError(f'pod {self} sees no accelerators')
        return ','.join(str(g) for g in self.accelerators)

    get_visible_gpus = get_visible_accelerators

    def __str__(self):
        return (f'rank:{self.rank} id:{self.id} addr:{self.addr} '
                f'port:{self.port} accelerators:{self.accelerators} '
                f'trainers:{[str(t) for t in self.trainers]}')


class Cluster:
    """All pods of one job (reference utils.py:141)."""

    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods = []
        self.hdfs = hdfs
        self.job_stage_flag = None

    def __eq__(self, c):
        return (self.pods == c.pods
                and self.job_stage_flag == c.job_stage_flag)

    def __ne__(self, c):
        return not self == c

    def update_pods(self, cluster):
        self.pods = list(cluster.pods)

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def pods_nranks(self):
        return len(self.pods)

    def trainers_endpoints(self):
        return [t.endpoint for pod in self.pods for t in pod.trainers]

    def pods_endpoints(self):
        eps = []
        for pod in self.pods:
            if pod.addr is None or pod.port is None:
                raise ValueError(f'{pod.addr}:{pod.port} is not a valid '
                                 'endpoint')
            eps.append(f'{pod.addr}:{pod.port}')
        return eps

    def get_pod_by_id(self, pod_id):
        for pod in self.pods:
            if str(pod.id) == str(pod_id):
                return pod
        return None

    def __str__(self):
        return (f'job_server:{self.job_server} '
                f'pods:{[str(p) for p in self.pods]} '
                f'job_stage_flag:{self.job_stage_flag} hdfs:{self.hdfs}')


def get_cluster(node_ips, node_ip, trainer_endpoints, selected_devices):
    """Build the Cluster/Pod topology (reference utils.py:316) and
    return (cluster, current_pod).  `trainer_endpoints` is one endpoint
    list per node; `selected_devices` the per-node chip indices."""
    if not isinstance(trainer_endpoints, list):
        raise TypeError('trainer_endpoints must be a list (one list of '
                        'endpoints per node)')
    cluster = Cluster()
    rank = 0
    for node_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = node_rank
        pod.id = node_rank
        pod.addr = ip
        pod.accelerators = list(selected_devices)
        eps = trainer_endpoints[node_rank]
        if len(eps) < len(selected_devices):
            raise ValueError(
                f'node {ip} has {len(eps)} endpoints for '
                f'{len(selected_devices)} selected devices')
        for dev, ep in zip(selected_devices, eps):
            t = Trainer()
            t.accelerators.append(dev)
            t.endpoint = ep
            t.rank = rank
            rank += 1
            pod.trainers.append(t)
        cluster.pods.append(pod)
    return cluster, cluster.pods[node_ips.index(node_ip)]


def get_host_name_ip():
    try:
        name = socket.gethostname()
        return name, socket.gethostbyname(name)
    except OSError:
        return None


def find_free_ports(num):
    """Reserve `num` distinct free TCP ports (reference utils.py:396)."""
    ports = set()
    socks = []
    try:
        for _ in range(num * 4):
            if len(ports) >= num:
                break
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(('', 0))
            p = s.getsockname()[1]
            if p not in ports:
                ports.add(p)
                socks.append(s)   # hold open so the next bind differs
            else:
                s.close()
    finally:
        for s in socks:
            s.close()
    return ports if len(ports) >= num else None


def add_arguments(argname, type, default, help, argparser, **kwargs):
    """argparse helper (reference utils.py:379): booleans accept
    true/false strings."""
    bool_t = (lambda v: str(v).lower() == 'true') if type == bool else type
    argparser.add_argument('--' + argname, default=default, type=bool_t,
                           help=help + f' Default: %(default)s.', **kwargs)


TrainerProc = _elastic.TrainerProc


def _trainer_env(cluster, trainer):
    return {
        'TPU_VISIBLE_DEVICES': ','.join(
            str(g) for g in trainer.accelerators),
        'PADDLE_TRAINER_ID': str(trainer.rank),
        'PADDLE_CURRENT_ENDPOINT': str(trainer.endpoint),
        'PADDLE_TRAINERS_NUM': str(cluster.trainers_nranks()),
        'PADDLE_TRAINER_ENDPOINTS': ','.join(
            cluster.trainers_endpoints()),
    }


def start_local_trainers(cluster, pod, training_script,
                         training_script_args, log_dir=None):
    """Spawn this pod's trainers (reference utils.py:454) with the
    paddle env-var contract set per trainer."""
    procs = []
    for local_rank, t in enumerate(pod.trainers):
        env = dict(os.environ)
        env.pop('http_proxy', None)
        env.pop('https_proxy', None)
        env.update(_trainer_env(cluster, t))
        cmd = [sys.executable, '-u', training_script] \
            + list(training_script_args)
        tp = TrainerProc()
        tp.rank = t.rank
        tp.local_rank = local_rank
        tp.cmd = cmd
        tp.env = env
        fn = None
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            fn = open(os.path.join(log_dir, f'workerlog.{local_rank}'),
                      'ab')
        tp.log_fn = fn
        tp.log_offset = fn.tell() if fn else None
        tp.proc = subprocess.Popen(cmd, env=env, stdout=fn or None,
                                   stderr=fn or None)
        procs.append(tp)
    return procs


def pull_worker_log(tp):
    """Stream a worker's log growth to stdout (reference utils.py:499)."""
    if not tp.log_fn:
        return
    tp.log_fn.flush()
    with open(tp.log_fn.name, 'rb') as f:
        f.seek(tp.log_offset or 0)
        chunk = f.read()
        tp.log_offset = f.tell()
    if chunk:
        sys.stdout.write(chunk.decode('utf-8', 'replace'))


def watch_local_trainers(procs, nranks):
    """One poll pass over the pod's trainers (reference utils.py:514):
    returns the still-alive list, [] when all exited cleanly, and
    terminates everything on the first failure."""
    alive = []
    failed = []
    for tp in procs:
        if tp.log_fn is not None and tp.local_rank == 0:
            pull_worker_log(tp)
        ret = tp.proc.poll()
        if ret is None:
            alive.append(tp)
        else:
            if tp.log_fn is not None and not tp.log_fn.closed:
                tp.log_fn.close()
            if ret != 0:
                failed.append(tp.rank)
    if failed:
        terminate_local_procs(procs)
        raise RuntimeError(
            f'trainer ranks {failed} exited abnormally '
            f'({nranks} total); local trainers terminated')
    return alive


def terminate_local_procs(procs, grace=3.0):
    """Reference utils.py:343 / launch_utils.py:308 — delegate to the
    elastic launcher's terminate (SIGTERM, grace wait, SIGKILL; it also
    closes and clears each TrainerProc's log_fn)."""
    _elastic.terminate_local_procs(procs, grace=grace)
