"""Fleet file datasets: InMemoryDataset / QueueDataset.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/dataset/dataset.py
(InMemoryDataset:253, QueueDataset:1086) — C++ multi-thread file readers
feeding the PS trainer by slot; InMemoryDataset additionally loads all
samples into host memory for local/global shuffle.

TPU-native: the C++ reader pipeline is paddle_tpu.io's prefetch-ring
DataLoader; these classes keep the fleet-facing API (init/set_filelist/
load_into_memory/local_shuffle/...) and expose the samples as an
IterableDataset, so `DataLoader(dataset.as_dataset(), ...)` feeds the
device the standard way.  File format: one sample per line, whitespace-
separated float/int fields matching `use_var` order and widths.
"""
import glob as _glob
import random

import numpy as np

from ..io import IterableDataset

__all__ = ['DatasetBase', 'InMemoryDataset', 'QueueDataset']


class _SlotSpec:
    def __init__(self, name, width, dtype):
        self.name, self.width, self.dtype = name, width, dtype


class DatasetBase:
    """Shared init/filelist handling (reference DatasetBase)."""

    def __init__(self):
        self._filelist = []
        self._slots = []
        self._batch_size = 1
        self._thread_num = 1
        self._pipe_command = None

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name='', fs_ugi='',
             download_cmd='cat', **kwargs):
        self._batch_size = int(batch_size)
        self._thread_num = int(thread_num)
        self._pipe_command = pipe_command
        self._slots = []
        for v in (use_var or []):
            shape = getattr(v, '_declared_shape', None) or \
                getattr(v, 'shape', [1])
            width = 1
            for d in shape[1:] if len(shape) > 1 else shape:
                if d and d > 0:
                    width *= int(d)
            dt = np.dtype(str(getattr(v, 'dtype', 'float32')))
            self._slots.append(_SlotSpec(
                getattr(v, 'name', f'slot_{len(self._slots)}'), width, dt))

    def set_filelist(self, filelist):
        files = []
        for f in filelist:
            hits = sorted(_glob.glob(f))
            files.extend(hits if hits else [f])
        self._filelist = files

    def _parse_line(self, line):
        toks = line.split()
        out, i = [], 0
        for s in self._slots:
            vals = toks[i:i + s.width]
            i += s.width
            out.append(np.asarray(vals, s.dtype).reshape(
                (s.width,) if s.width > 1 else (1,)))
        return tuple(out) if len(out) > 1 else out[0]

    _CHUNK = 1 << 20   # streaming native-parse granularity (1 MB)

    def _iter_files(self):
        """Streaming parse with BOUNDED memory, used by QueueDataset
        (matching the reference's streaming pipe readers): reads ~1 MB
        chunks of complete lines and hands each to the C++ parser
        (io/native/slotreader.sr_parse_buf); pure-Python line parse
        without a compiler or for non-{int64,float32} slot dtypes."""
        from ..io.native import slotreader
        native_ok = self._slots and slotreader.available() and all(
            s.dtype == np.int64 or s.dtype == np.float32
            for s in self._slots)
        widths = [s.width for s in self._slots]
        ints = [np.issubdtype(s.dtype, np.integer) for s in self._slots]
        for path in self._filelist:
            if not native_ok:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield self._parse_line(line)
                continue
            with open(path, 'rb') as f:
                carry = b''
                while True:
                    chunk = f.read(self._CHUNK)
                    if not chunk:
                        break
                    chunk = carry + chunk
                    cut = chunk.rfind(b'\n')
                    if cut < 0:           # no complete line yet
                        carry = chunk
                        continue
                    carry = chunk[cut + 1:]
                    cols = slotreader.parse_bytes(
                        chunk[:cut + 1], widths, ints, origin=path)
                    yield from self._rows_of(cols)
                if carry.strip():
                    cols = slotreader.parse_bytes(carry, widths, ints,
                                                  origin=path)
                    yield from self._rows_of(cols)

    @staticmethod
    def _rows_of(cols):
        n = cols[0].shape[0] if cols else 0
        for r in range(n):
            row = tuple(c[r] for c in cols)
            yield row if len(row) > 1 else row[0]

    def _iter_files_bulk(self):
        """Whole-file parse via the C++ slot parser (io/native/
        slotreader — the reference's MultiSlotDataFeed counterpart):
        one native pass per file, columns sliced into rows.  ONLY for
        consumers that materialize everything anyway
        (InMemoryDataset.load_into_memory) — a streaming consumer would
        lose its constant-memory contract.  Falls back to the streaming
        parser without a compiler or for slot dtypes other than
        int64/float32 (those keep their declared dtypes)."""
        from ..io.native import slotreader
        native_ok = self._slots and all(
            s.dtype == np.int64 or s.dtype == np.float32
            for s in self._slots)
        for path in self._filelist:
            cols = None
            if native_ok:
                cols = slotreader.parse_file(
                    path, [s.width for s in self._slots],
                    [np.issubdtype(s.dtype, np.integer)
                     for s in self._slots])
            if cols is not None:
                yield from self._rows_of(cols)
                continue
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield self._parse_line(line)


class _IterView(IterableDataset):
    def __init__(self, it_fn):
        self._it_fn = it_fn

    def __iter__(self):
        return iter(self._it_fn())


class QueueDataset(DatasetBase):
    """Streaming file dataset (no shuffle buffer): samples flow straight
    from the files, like the reference's QueueDataset pipe readers."""

    def as_dataset(self):
        return _IterView(self._iter_files)

    def __iter__(self):
        return self._iter_files()


class InMemoryDataset(DatasetBase):
    """Loads every sample into host memory; supports local_shuffle and
    (API-compat) global_shuffle before iteration."""

    def __init__(self):
        super().__init__()
        self._samples = None

    def load_into_memory(self):
        self._samples = list(self._iter_files_bulk())

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        if self._samples is None:
            raise RuntimeError('call load_into_memory() first')
        random.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12):
        # one host == one shard here, so a global shuffle IS the local one
        self.local_shuffle()

    def release_memory(self):
        self._samples = None

    def get_memory_data_size(self, fleet=None):
        return len(self._samples or [])

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    def as_dataset(self):
        def gen():
            if self._samples is None:
                raise RuntimeError('call load_into_memory() first')
            return iter(self._samples)
        return _IterView(gen)

    def __iter__(self):
        if self._samples is None:
            raise RuntimeError('call load_into_memory() first')
        return iter(self._samples)
