"""Model-parallel op: paddle.distributed.split.

Reference analogue: /root/reference/python/paddle/distributed/collective.py:1108
— splits the weight of a linear/embedding op across ranks (parallel
embedding, row-parallel linear, column-parallel linear) with NCCL
gather/allreduce glue.

TPU-native: the three cases ARE fleet.meta_parallel's TP layers with
'tp'-axis PartitionSpecs; XLA inserts the collectives.  split() builds
the matching layer once per call site (build-time API, like the
reference, which creates the program weights on first call) and applies
it.  num_partitions must match the installed mesh's tp axis (or 1 when
no mesh is installed — degrades to the dense op, same as the reference
on one rank).
"""
import warnings

from . import env as _env

__all__ = ['split']


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    from .fleet.meta_parallel import (ColumnParallelLinear,
                                      RowParallelLinear,
                                      VocabParallelEmbedding)

    mesh = _env.get_mesh()
    tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get('tp', 1) \
        if mesh is not None else 1
    if num_partitions not in (1, tp):
        warnings.warn(
            f'distributed.split: num_partitions={num_partitions} does not '
            f'match the mesh tp axis ({tp}); the sharding follows the '
            'mesh', stacklevel=2)

    if operation == 'embedding':
        num_emb, dim = size
        layer = VocabParallelEmbedding(num_emb, dim,
                                       weight_attr=weight_attr, name=name)
        return layer(x)
    if operation != 'linear':
        raise ValueError("operation must be 'linear' or 'embedding', "
                         f"got {operation!r}")
    in_f, out_f = size
    if axis == 0:    # weight rows split -> row-parallel
        layer = RowParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                  has_bias=bias_attr is not False,
                                  input_is_parallel=False, name=name)
        return layer(x)
    if axis == 1:    # weight cols split -> column-parallel
        layer = ColumnParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                     has_bias=bias_attr is not False,
                                     gather_output=gather_out, name=name)
        return layer(x)
    raise ValueError(f'axis must be 0 or 1, got {axis}')
