"""Model-parallel op: paddle.distributed.split.

Reference analogue: /root/reference/python/paddle/distributed/collective.py:1108
— splits the weight of a linear/embedding op across ranks (parallel
embedding, row-parallel linear, column-parallel linear) with NCCL
gather/allreduce glue.

TPU-native: the three cases ARE fleet.meta_parallel's TP layers with
'tp'-axis PartitionSpecs; XLA inserts the collectives.  split() is a
BUILD-time API (the reference creates program weights once while the
static graph is recorded).  Semantics here:

  * static mode / first build: a fresh TP layer each call — each
    recorded op owns its weights, like the reference;
  * eager loop with `name=`: the layer is cached per (name, spec,
    global seed) and reused, so repeated calls train ONE weight;
  * eager loop without `name`: reference dygraph behavior — a fresh
    layer (fresh weights!) per call, with a one-time warning, because
    a hidden cache keyed on call-site silently SHARES weights between
    distinct layers built in a loop at one source line.
"""
import warnings

from . import env as _env

__all__ = ['split']

# name-keyed layer reuse for eager training loops; (name, spec, seed) —
# paddle.seed() between model builds must yield fresh weights
_LAYER_CACHE = {}
_WARNED_UNNAMED = [False]


def _build(operation, size, axis, gather_out, weight_attr, bias_attr,
           name):
    from .fleet.meta_parallel import (ColumnParallelLinear,
                                      RowParallelLinear,
                                      VocabParallelEmbedding)
    if operation == 'embedding':
        num_emb, dim = size
        return VocabParallelEmbedding(num_emb, dim,
                                      weight_attr=weight_attr, name=name)
    if operation != 'linear':
        raise ValueError("operation must be 'linear' or 'embedding', "
                         f"got {operation!r}")
    in_f, out_f = size
    if axis == 0:    # weight rows split -> row-parallel
        return RowParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                 has_bias=bias_attr is not False,
                                 input_is_parallel=False, name=name)
    if axis == 1:    # weight cols split -> column-parallel
        return ColumnParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                    has_bias=bias_attr is not False,
                                    gather_output=gather_out, name=name)
    raise ValueError(f'axis must be 0 or 1, got {axis}')


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    mesh = _env.get_mesh()
    tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get('tp', 1) \
        if mesh is not None else 1
    if num_partitions not in (1, tp):
        warnings.warn(
            f'distributed.split: num_partitions={num_partitions} does not '
            f'match the mesh tp axis ({tp}); the sharding follows the '
            'mesh', stacklevel=2)

    from ..static.program import in_static_mode
    # static recording ALWAYS builds fresh weights per call — each
    # recorded op owns its parameters, like the reference's program
    # build.  The eager name-cache also skips custom weight_attr: a
    # ParamAttr has no value-based identity, so caching on it would
    # either poison (id reuse) or silently ignore a new initializer.
    if name is not None and not in_static_mode() and weight_attr is None:
        from ..core import rng as _rng
        # mesh identity in the key: after re-init with another tp
        # degree, a cached layer would keep stale per-shard weight
        # shapes/shardings (Mesh hashes by devices+axis names; set_mesh
        # additionally evicts the cache on every topology change)
        key = (name, operation, tuple(size), axis, num_partitions,
               gather_out, bias_attr is not False, _rng.get_seed(),
               mesh)
        layer = _LAYER_CACHE.get(key)
        if layer is None:
            layer = _LAYER_CACHE[key] = _build(
                operation, size, axis, gather_out, weight_attr,
                bias_attr, name)
        return layer(x)

    if not in_static_mode() and not _WARNED_UNNAMED[0]:
        _WARNED_UNNAMED[0] = True
        why = ('without name=' if name is None
               else 'with a custom weight_attr (no value-based cache '
                    'identity)')
        warnings.warn(
            f'distributed.split {why} creates FRESH weights on every '
            'eager call (reference dygraph semantics) — pass name= '
            'without weight_attr to reuse one layer across steps, or '
            'use the fleet.meta_parallel layer classes directly',
            stacklevel=2)
    return _build(operation, size, axis, gather_out, weight_attr,
                  bias_attr, name)(x)
