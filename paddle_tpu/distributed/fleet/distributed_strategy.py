"""DistributedStrategy — training strategy configuration.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/base/distributed_strategy.py
(a protobuf of ~40 toggles consumed by meta_optimizers).  Here it is a
plain object; each toggle maps to a TPU mechanism:

  amp                → bf16 policy in the compiled step (paddle_tpu.amp)
  recompute          → jax.checkpoint around listed blocks
  sharding (ZeRO)    → optimizer state NamedSharding over 'dp'
  pipeline           → 'pp' mesh axis + shard_map GPipe engine
  tensor_parallel    → 'tp' mesh axis + parallel_layers shardings
  sequence_parallel  → 'sp' mesh axis + ring attention
  gradient_merge     → lax.scan microbatch accumulation
  lamb/lars          → optimizer core swap
  localsgd           → periodic param psum instead of per-step grad sync
  dgc                → top-k grad compression (documented stub on TPU —
                       ICI bandwidth makes it counterproductive)
"""

__all__ = ['DistributedStrategy']


class _Bag(dict):
    __getattr__ = dict.get

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        self.amp = False
        self.amp_configs = _Bag(init_loss_scaling=32768.0, use_pure_fp16=False,
                                custom_white_list=None, custom_black_list=None,
                                use_bf16=True)
        self.recompute = False
        self.recompute_configs = _Bag(checkpoints=[], policy='nothing_saveable')
        self.sharding = False
        self.sharding_configs = _Bag(stage=1, sharding_degree=-1)
        self.pipeline = False
        self.pipeline_configs = _Bag(accumulate_steps=1, micro_batch_size=1,
                                     schedule_mode='1F1B')
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Bag(tensor_parallel_degree=1)
        self.sequence_parallel = False
        self.gradient_merge = False
        self.gradient_merge_configs = _Bag(k_steps=1, avg=True)
        self.lamb = False
        self.lamb_configs = _Bag(lamb_weight_decay=0.01, exclude_from_weight_decay=[])
        self.lars = False
        self.lars_configs = _Bag(lars_coeff=0.001, lars_weight_decay=0.0005)
        self.localsgd = False
        self.localsgd_configs = _Bag(k_steps=1)
        self.dgc = False
        self.dgc_configs = _Bag(rampup_begin_step=0, rampup_step=1,
                                sparsity=[0.999])
        self.a_sync = False
        self.a_sync_configs = _Bag(k_steps=-1)
        self.hybrid_configs = _Bag(dp_degree=-1, mp_degree=1, pp_degree=1,
                                   sp_degree=1, ep_degree=1,
                                   sharding_degree=1)
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True   # XLA always fuses; kept for parity
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1            # meaningless on ICI; parity only

    # the reference exposes hybrid_configs via dict-style assignment
    @property
    def hybrid_parallel_order(self):
        return ['pp', 'dp', 'sp', 'ep', 'mp']

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on}, hybrid={dict(self.hybrid_configs)})"
