"""Role makers + fleet.util.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/base/role_maker.py
(PaddleCloudRoleMaker reads cluster env vars; UserDefinedRoleMaker takes
explicit endpoints; Role.WORKER/SERVER) and base/util_factory.py
(UtilBase — host-side collectives + file sharding).

TPU-native: "workers" are HOST processes (one per host, driving all its
chips); there are no parameter-server processes — the PS substitute is
incubate.HostOffloadEmbedding — so SERVER roles exist for API parity and
always report zero servers unless explicitly configured.
"""
import os

__all__ = ['Role', 'PaddleCloudRoleMaker', 'UserDefinedRoleMaker',
           'UtilBase']


class Role:
    WORKER = 1
    SERVER = 2


class _RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role = Role.WORKER
        self._current_id = 0

    def _worker_index(self):
        import jax
        return jax.process_index()

    def _worker_num(self):
        import jax
        return jax.process_count()

    def _is_worker(self):
        return self._role == Role.WORKER

    def _is_server(self):
        return self._role == Role.SERVER

    def _is_first_worker(self):
        return self._is_worker() and self._worker_index() == 0

    def _get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def _get_pserver_endpoints(self):
        return list(self._server_endpoints)

    def _server_num(self):
        return len(self._server_endpoints)


class PaddleCloudRoleMaker(_RoleMakerBase):
    """Reads the launch environment (reference reads PADDLE_* env vars
    set by paddle.distributed.launch; here the JAX distributed runtime
    already knows process_index/count, and PADDLE_TRAINER_ENDPOINTS is
    honored when present for parity)."""

    def __init__(self, is_collective=True, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
        self._worker_endpoints = [e for e in eps.split(',') if e]
        seps = os.environ.get('PADDLE_PSERVERS_IP_PORT_LIST', '')
        self._server_endpoints = [e for e in seps.split(',') if e]


class UserDefinedRoleMaker(_RoleMakerBase):
    """Explicit topology (reference UserDefinedRoleMaker)."""

    def __init__(self, is_collective=False, init_gloo=False,
                 current_id=0, role=Role.WORKER, worker_num=1,
                 worker_endpoints=None, server_endpoints=None, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = list(worker_endpoints or [])
        self._server_endpoints = list(server_endpoints or [])
        self._user_worker_num = worker_num

    def _worker_index(self):
        return self._current_id

    def _worker_num(self):
        return self._user_worker_num


class UtilBase:
    """fleet.util (reference base/util_factory.py::UtilBase): host-side
    helpers that are NOT part of the compiled step — cross-host reduce
    of python scalars, barriers, and input-file sharding."""

    def __init__(self, role_maker=None):
        self._role_maker = role_maker

    def _pcount(self):
        if self._role_maker is not None:
            return self._role_maker._worker_num()
        import jax
        return jax.process_count()

    def _pindex(self):
        if self._role_maker is not None:
            return self._role_maker._worker_index()
        import jax
        return jax.process_index()

    def all_reduce(self, input, mode='sum', comm_world='worker'):
        """Reduce a host value across host processes.  Multi-host rides
        jax's global collective over a tiny device array; single-host is
        the identity."""
        import numpy as np
        if self._pcount() == 1:
            arr = np.asarray(input)
            if mode == 'sum':
                return arr
            return arr  # min/max of one participant is itself
        from jax.experimental import multihost_utils
        import jax.numpy as jnp
        arr = jnp.asarray(input)
        ops = {'sum': jnp.sum, 'min': jnp.min, 'max': jnp.max}
        stacked = multihost_utils.process_allgather(arr)
        return np.asarray(ops[mode](stacked, axis=0))

    def barrier(self, comm_world='worker'):
        if self._pcount() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices('fleet_util_barrier')

    def get_file_shard(self, files):
        """Split a file list evenly over workers (reference contract:
        earlier workers take the remainder)."""
        if not isinstance(files, list):
            raise TypeError('files should be a list of file paths')
        n, i = self._pcount(), self._pindex()
        base, rem = divmod(len(files), n)
        begin = i * base + min(i, rem)
        return files[begin: begin + base + (1 if i < rem else 0)]

    def print_on_rank(self, message, rank_id=0):
        if self._pindex() == rank_id:
            print(message, flush=True)
