"""fleet.metrics — metrics aggregated across all trainers.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/metrics/metric.py
(sum:24, max:64, min:104, auc:144, mae:227, rmse:276, mse:325,
acc:373): each worker keeps local accumulators, and these helpers
MPI-allreduce them before the final formula.

TPU-native: the aggregation has two routes, picked automatically —

  * INSIDE a compiled step (`shard_map` with a bound mesh axis) the
    reduce is a `lax.psum`/`pmax`/`pmin` over the data-parallel axis,
    riding the same ICI collectives as the gradients (no host round
    trip, jit-safe);
  * OUTSIDE (host numpy, the reference's scope/util mode) it goes
    through `fleet.util.all_reduce`, which is a no-op single-process
    and a tiny process_allgather multi-host.

Inputs may be numpy arrays, paddle Tensors, or traced jnp arrays; the
scope/util kwargs of the reference are accepted (scope is meaningless
without a ProgramDesc scope and ignored; util overrides the default
fleet.util).
"""
import builtins
import math

import numpy as np

__all__ = ['sum', 'max', 'min', 'auc', 'mae', 'rmse', 'mse', 'acc']


def _axis_bound(axis):
    import jax
    try:
        jax.lax.axis_index(axis)
        return True
    except Exception:
        return False


def _tracing(x):
    import jax.core
    return isinstance(x, jax.core.Tracer)


def _unwrap(x):
    v = getattr(x, 'value', x)
    return v


def _default_util():
    from ..fleet_base import get_fleet
    return get_fleet().util


def _reduce(value, mode, util=None, axis='dp'):
    """All-trainer reduce: in-trace psum over the mesh axis, host
    all_reduce otherwise."""
    v = _unwrap(value)
    if _tracing(v) or _axis_bound(axis):
        import jax
        import jax.numpy as jnp
        v = jnp.asarray(v)
        if _axis_bound(axis):
            op = {'sum': jax.lax.psum, 'max': jax.lax.pmax,
                  'min': jax.lax.pmin}[mode]
            return op(v, axis)
        return v  # traced but unmapped: single logical trainer
    arr = np.asarray(v)
    if util is None:
        util = _default_util()
    out = util.all_reduce(arr.reshape(-1), mode)
    return np.asarray(out).reshape(arr.shape)


def sum(input, scope=None, util=None):
    """Distributed sum (reference metric.py:24)."""
    return _reduce(input, 'sum', util)


def max(input, scope=None, util=None):
    """Distributed elementwise max (reference metric.py:64)."""
    return _reduce(input, 'max', util)


def min(input, scope=None, util=None):
    """Distributed elementwise min (reference metric.py:104)."""
    return _reduce(input, 'min', util)


def _auc_from_buckets(global_pos, global_neg):
    """Reference metric.py:203-226: walk buckets high→low, trapezoid
    area over the (neg, pos) cumulative counts."""
    pos_b = np.asarray(global_pos, np.float64).reshape(-1)
    neg_b = np.asarray(global_neg, np.float64).reshape(-1)
    area = 0.0
    pos = neg = 0.0
    total = 0.0
    for index in range(len(pos_b) - 1, -1, -1):
        new_pos = pos + pos_b[index]
        new_neg = neg + neg_b[index]
        total += pos_b[index] + neg_b[index]
        area += (new_neg - neg) * (pos + new_pos) / 2
        pos, neg = new_pos, new_neg
    if pos * neg == 0 or total == 0:
        return 0.5
    return float(area / (pos * neg))


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Distributed AUC from per-worker histogram buckets (reference
    metric.py:144): allreduce-sum the pos/neg bucket counts, then the
    trapezoid walk.  Buckets are what `paddle.metric.Auc` keeps in
    `_stat_pos`/`_stat_neg` (or the reference fluid.layers.auc
    StatPos/StatNeg vars, shape [N] or [1, N])."""
    global_pos = _reduce(np.asarray(_unwrap(stat_pos)), 'sum', util)
    global_neg = _reduce(np.asarray(_unwrap(stat_neg)), 'sum', util)
    return _auc_from_buckets(global_pos, global_neg)


def mae(abserr, total_ins_num, scope=None, util=None):
    """Distributed MAE (reference metric.py:227): global sum of abs
    error over global instance count."""
    g = np.asarray(_reduce(abserr, 'sum', util)).reshape(-1)
    n = np.asarray(_reduce(total_ins_num, 'sum', util)).reshape(-1)
    return float(g[0]) / float(n[0])


def mse(sqrerr, total_ins_num, scope=None, util=None):
    """Distributed MSE (reference metric.py:325)."""
    g = np.asarray(_reduce(sqrerr, 'sum', util)).reshape(-1)
    n = np.asarray(_reduce(total_ins_num, 'sum', util)).reshape(-1)
    return float(g[0]) / float(n[0])


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    """Distributed RMSE (reference metric.py:276)."""
    return math.sqrt(mse(sqrerr, total_ins_num, scope, util))


def acc(correct, total, scope=None, util=None):
    """Distributed accuracy (reference metric.py:373): global correct
    count over global sample count."""
    c = np.asarray(_reduce(correct, 'sum', util)).reshape(-1)
    t = np.asarray(_reduce(total, 'sum', util)).reshape(-1)
    return float(c[0]) / float(t[0])
