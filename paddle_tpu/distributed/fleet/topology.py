"""Logical process/chip topology.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/base/topology.py:35
(CommunicateTopology — a named N-D grid over global ranks answering
rank<->coordinate queries and enumerating communication groups).

TPU-native: the grid IS the jax.sharding.Mesh; "ranks" here are logical
device indices in the mesh's row-major order.  The class stays
mesh-independent (plain names+shape arithmetic) so it also describes
topologies that are not currently installed.
"""
import itertools

import numpy as np

__all__ = ['CommunicateTopology']


class CommunicateTopology:
    def __init__(self, hybrid_group_names=('data', 'pipe', 'sharding',
                                           'model'),
                 dims=(1, 1, 1, 1)):
        if len(hybrid_group_names) != len(dims):
            raise ValueError('names and dims must align')
        self._names = list(hybrid_group_names)
        self._dims = [int(d) for d in dims]
        self._world = int(np.prod(self._dims))
        coords = list(itertools.product(*[range(d) for d in self._dims]))
        self._coord_of_rank = {r: c for r, c in enumerate(coords)}
        self._rank_of_coord = {c: r for r, c in enumerate(coords)}

    @classmethod
    def from_mesh(cls, mesh):
        """Describe an installed jax Mesh (axis order preserved)."""
        return cls(tuple(mesh.axis_names), tuple(mesh.devices.shape))

    def get_hybrid_group_names(self):
        return list(self._names)

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **coords):
        if sorted(coords) != sorted(self._names):
            raise ValueError(f'need every axis of {self._names}, '
                             f'got {sorted(coords)}')
        key = tuple(coords[n] for n in self._names)
        return self._rank_of_coord[key]

    def get_coord(self, rank):
        return self._coord_of_rank[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate along axis_name equals index."""
        ax = self._names.index(axis_name)
        return [r for r, c in self._coord_of_rank.items()
                if c[ax] == index]

    def get_comm_list(self, axis_name):
        """Groups of ranks that communicate along axis_name: one list
        per combination of the OTHER axes' coordinates."""
        ax = self._names.index(axis_name)
        others = [range(d) for i, d in enumerate(self._dims) if i != ax]
        out = []
        for combo in itertools.product(*others):
            group = []
            for v in range(self._dims[ax]):
                c = list(combo)
                c.insert(ax, v)
                group.append(self._rank_of_coord[tuple(c)])
            out.append(group)
        return out
