"""fleet.utils — filesystem helpers, PS-infer shim, recompute.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/utils/__init__.py
(LocalFS/HDFSClient from fs.py, DistributedInfer from ps_util.py,
recompute from recompute.py).
"""
from .fs import LocalFS, HDFSClient  # noqa: F401
from .ps_util import DistributedInfer  # noqa: F401
from .recompute import recompute  # noqa: F401

__all__ = ['LocalFS', 'HDFSClient', 'recompute', 'DistributedInfer']
