"""Filesystem clients (reference fleet/utils/fs.py).

LocalFS is a real local implementation; HDFSClient shells out to the
`hadoop` binary exactly like the reference and therefore raises at
construction when no hadoop client is installed (this environment is
zero-egress), instead of failing mysteriously on first use.
"""
import os
import shutil
import subprocess

__all__ = ['LocalFS', 'HDFSClient']


class ExecuteError(Exception):
    pass


class LocalFS:
    """Reference fs.py::LocalFS — thin, explicit local-disk API."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        else:
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FileExistsError(fs_path)
            return
        open(fs_path, 'a').close()

    def mv(self, src_path, dst_path, overwrite=False):
        if not overwrite and self.is_exist(dst_path):
            raise FileExistsError(dst_path)
        shutil.move(src_path, dst_path)

    def list_dirs(self, fs_path):
        dirs, _ = self.ls_dir(fs_path)
        return dirs


class HDFSClient:
    """Reference fs.py::HDFSClient drives `hadoop fs -...` subcommands.
    Kept command-compatible; requires a hadoop client on PATH."""

    def __init__(self, hadoop_home=None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, 'bin', 'hadoop') \
            if hadoop_home else shutil.which('hadoop')
        if not self._hadoop or not os.path.exists(self._hadoop):
            raise RuntimeError(
                'HDFSClient needs a hadoop client binary (none found on '
                'PATH and this environment is zero-egress); use LocalFS, '
                'or distributed.checkpoint for sharded model state')
        self._configs = [f'-D{k}={v}'
                         for k, v in (configs or {}).items()]
        self._timeout = time_out / 1000.0

    def _run(self, *args):
        cmd = [self._hadoop, 'fs', *self._configs, *args]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=self._timeout)
        if proc.returncode != 0:
            raise ExecuteError(f'{" ".join(cmd)}: {proc.stderr}')
        return proc.stdout

    def ls_dir(self, fs_path):
        out = self._run('-ls', fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith('d') else files).append(name)
        return dirs, files

    def is_exist(self, fs_path):
        try:
            self._run('-test', '-e', fs_path)
            return True
        except ExecuteError:
            return False

    def mkdirs(self, fs_path):
        self._run('-mkdir', '-p', fs_path)

    def delete(self, fs_path):
        self._run('-rm', '-r', fs_path)

    def upload(self, local_path, fs_path):
        self._run('-put', local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run('-get', fs_path, local_path)

    def need_upload_download(self):
        return True
