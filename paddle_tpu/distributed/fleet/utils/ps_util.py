"""Parameter-server inference shim.

Reference analogue: fleet/utils/ps_util.py::DistributedInfer — in PS
mode the sparse tables live on remote servers, so inference first pulls
the needed rows into the local program.

TPU-native: the PS substitute keeps tables on the LOCAL host
(incubate.HostOffloadEmbedding) or dense on the mesh, so there is
nothing to pull — init is a no-op and the wrapped program is returned
unchanged.  The class exists so reference inference scripts run.
"""

__all__ = ['DistributedInfer']


class DistributedInfer:
    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program
        self._startup = startup_program

    def init_distributed_infer_env(self, exe=None, loss=None,
                                   role_maker=None, dirname=None):
        """No remote tables to pull on TPU — sparse state is already
        host-local; load a checkpoint via paddle_tpu.static.load or
        distributed.load_sharded instead of a PS pull."""
        return None

    def get_dist_infer_program(self):
        return self._main
