"""Activation recompute as a user-facing function.

Reference analogue: fleet/utils/recompute.py::recompute — wraps a block
so its activations are NOT stored for backward; they are recomputed
from the block's inputs during the backward pass (the reference
re-runs the block under a RecomputeFunction autograd node).

TPU-native: jax.checkpoint over the block.  Inside a compiled train
step (jit.to_static / ParallelTrainer / hapi) XLA rematerializes the
block in the backward — the same memory/FLOPs trade, scheduled by the
compiler.  ParallelTrainer's `strategy.recompute = True` applies this
per-block automatically; this function is the explicit per-call-site
form.

Gradient scope: like jax.checkpoint, gradients flow through the
TENSOR ARGUMENTS.  Layer parameters captured by closure receive
gradients when the surrounding step is functionally captured (the
compiled paths above); in eager mode pass them as explicit args if you
need their `.grad` populated.
"""
import jax

from ....core.dispatch import apply
from ....core.tensor import Tensor

__all__ = ['recompute']


def recompute(function, *args, **kwargs):
    preserve_rng_state = kwargs.pop('preserve_rng_state', True)  # noqa: F841
    # (jax PRNG keys are explicit values, so they replay identically on
    # rematerialization — the reference's CUDA RNG stashing is moot)

    def pure(*vals):
        ts = [Tensor._from_value(v, stop_gradient=False) for v in vals]
        out = function(*ts, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(o.value if isinstance(o, Tensor) else o
                         for o in out)
        return out.value if isinstance(out, Tensor) else out

    return apply(jax.checkpoint(pure), *args, op_name='recompute')
