"""meta_parallel — tensor-parallel layers + pipeline structure.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/meta_parallel/
(parallel_layers/mp_layers.py: ColumnParallelLinear, RowParallelLinear,
VocabParallelEmbedding backed by c_identity/c_allreduce/c_allgather NCCL
ops; pp_layers.py: PipelineLayer/LayerDesc).  TPU-native: layers create
FULL logical parameters and attach a PartitionSpec per parameter
(`_param_shardings`); the compiled step (paddle_tpu.parallel.engine)
turns those into NamedShardings over the mesh and XLA's SPMD partitioner
inserts exactly the collectives the reference hand-codes — column split
= no comm forward / reduce-scatter backward, row split = psum forward.
Sharding-constraint hints inside forward keep the partitioner honest on
activation layouts.  Single chip, everything degrades to plain layers.
"""
import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, Parameter
from ...core import rng as rng_mod
from ...nn.layer.layers import Layer
from ...nn import functional as F
from ...nn import initializer as I
from ...parallel.api import maybe_shard

__all__ = ['ColumnParallelLinear', 'RowParallelLinear',
           'VocabParallelEmbedding', 'ParallelCrossEntropy',
           'PipelineLayer', 'LayerDesc', 'get_rng_state_tracker',
           'RNGStatesTracker']


class ColumnParallelLinear(Layer):
    """Y = XW + b with W column-split over 'tp'.

    Reference: mp_layers.py::ColumnParallelLinear (c_identity fwd,
    c_allreduce bwd).  Here: weight P(None,'tp'); XLA derives the
    comm pattern from shardings.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        w_init = getattr(weight_attr, 'initializer', None) if weight_attr \
            else None
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=w_init or I.XavierNormal())
        self.bias = self.create_parameter(
            shape=[out_features], attr=None, is_bias=True) if has_bias \
            else None
        self._param_shardings = {'weight': (None, 'tp'),
                                 'bias': ('tp',) if has_bias else None}

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return maybe_shard(y, None)      # replicated on tp
        return maybe_shard(y, ('...', 'tp'))  # last dim tp-sharded

    def extra_repr(self):
        return f"col-parallel {list(self.weight.shape)}"


class RowParallelLinear(Layer):
    """Y = XW + b with W row-split over 'tp'; forward needs a psum
    (XLA inserts it from the shardings).

    Reference: mp_layers.py::RowParallelLinear (c_allreduce_sum fwd).
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        w_init = getattr(weight_attr, 'initializer', None) if weight_attr \
            else None
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=w_init or I.XavierNormal())
        self.bias = self.create_parameter(
            shape=[out_features], attr=None, is_bias=True) if has_bias \
            else None
        self._param_shardings = {'weight': ('tp', None),
                                 'bias': None if self.bias is not None
                                 else None}

    def forward(self, x):
        if self.input_is_parallel:
            x = maybe_shard(x, ('...', 'tp'))
        y = F.linear(x, self.weight, self.bias)
        return maybe_shard(y, None)  # psum lands here under SPMD


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim split over 'tp'.

    Reference: mp_layers.py::VocabParallelEmbedding (masked local lookup
    + c_allreduce).  Under GSPMD the table is P('tp', None) and XLA
    partitions the gather the same way.
    """

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        w_init = getattr(weight_attr, 'initializer', None) if weight_attr \
            else None
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=w_init or I.XavierNormal())
        self._param_shardings = {'weight': ('tp', None)}

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """CE over tp-sharded logits.

    Reference: parallel_cross_entropy in mp_layers — a
    local-max/psum-logsumexp dance over NCCL.  With logits sharded
    P(...,'tp'), XLA's partitioner derives that same pattern from the
    ordinary fused CE.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction='none',
                               ignore_index=self.ignore_index)


# -- pipeline structure ------------------------------------------------------

class LayerDesc:
    """Deferred layer constructor (reference: pp_layers.py::LayerDesc) —
    lets PipelineLayer materialize parameters only on the owning stage."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr='weight',
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Reference: pp_layers.py::PipelineLayer — holds the full layer
    list, segments it into `num_stages` contiguous stages.  TPU engine
    options: (a) GSPMD: stage params live on 'pp' mesh rows, microbatch
    GPipe loop via shard_map+ppermute (parallel/pipeline.py); (b) single
    chip: plain sequential forward.  This class is the structure; the
    schedule lives in the engine.
    """

    def __init__(self, layers, num_stages=1, loss_fn=None, topology=None,
                 seg_method='uniform', recompute_interval=0, **kwargs):
        super().__init__()
        self.descs = list(layers)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        built = []
        for i, d in enumerate(self.descs):
            layer = d.build_layer() if isinstance(d, LayerDesc) else d
            built.append(layer)
            if isinstance(layer, Layer):
                self.add_sublayer(str(i), layer)
        self.run_function = built
        # contiguous uniform segmentation (reference default)
        n = len(built)
        per = int(np.ceil(n / num_stages))
        self.stage_bounds = [(s * per, min(n, (s + 1) * per))
                             for s in range(num_stages)]

    def stage_layers(self, stage_id):
        lo, hi = self.stage_bounds[stage_id]
        return self.run_function[lo:hi]

    def forward(self, x):
        for fn in self.run_function:
            x = fn(x)
        return x


# -- rng tracker -------------------------------------------------------------

class RNGStatesTracker:
    """Reference: parallel_layers/random.py::RNGStatesTracker — keeps
    named RNG streams so tp ranks drop the SAME units where weights are
    replicated and DIFFERENT units where they're sharded.  JAX version:
    named substreams fork the global key; 'model_parallel' additionally
    folds in the tp coordinate inside parallel regions."""

    def __init__(self):
        self.states = {}

    def add(self, name, seed):
        import jax
        self.states[name] = jax.random.PRNGKey(int(seed))

    def rng_state(self, name='model_parallel'):
        import contextlib

        @contextlib.contextmanager
        def scope():
            import jax
            if name not in self.states:
                self.add(name, hash(name) & 0x7fffffff)
            self.states[name], use = jax.random.split(self.states[name])
            from .. import collective
            if name == 'model_parallel' and 'tp' in collective.current_axes():
                import jax.lax as lax
                use = jax.random.fold_in(use, lax.axis_index('tp'))
            with rng_mod.functional_key_scope(use):
                yield
        return scope()


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker
