"""MultiSlot data generators.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/data_generator/data_generator.py
— users subclass, implement generate_sample(line) yielding
[(slot_name, values), ...]; run_from_stdin() turns raw logs into the
MultiSlot text format the dataset readers consume
(`<n> v1 .. vn` per slot, space-joined per sample line).

These pair with distributed.InMemoryDataset/QueueDataset, whose
file format is the whitespace slot layout this emits.
"""
import sys

__all__ = ['DataGenerator', 'MultiSlotDataGenerator',
           'MultiSlotStringDataGenerator']


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """Override: return a generator yielding
        [(slot_name, [values]), ...] per sample derived from `line`."""
        raise NotImplementedError(
            'implement generate_sample(self, line) in your subclass')

    def generate_batch(self, samples):
        """Override for batch-level postprocessing; default passthrough."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _format_sample(self, sample):
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            for sample in self._samples_of(line):
                sys.stdout.write(self._format_sample(sample) + '\n')

    def run_from_memory(self, lines):
        """Like run_from_stdin but over an iterable; returns the
        formatted lines (testable without process plumbing)."""
        out = []
        for line in lines:
            for sample in self._samples_of(line):
                out.append(self._format_sample(sample))
        return out

    def _samples_of(self, line):
        gen = self.generate_sample(line)
        if gen is None:
            return
        batch = []
        for sample in gen():
            batch.append(sample)
            if len(batch) == self.batch_size_:
                yield from self.generate_batch(batch)()
                batch = []
        if batch:
            yield from self.generate_batch(batch)()


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots: each becomes `<n> v1 ... vn`."""

    def _format_sample(self, sample):
        parts = []
        for name, values in sample:
            values = list(values)
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return ' '.join(parts)


class MultiSlotStringDataGenerator(DataGenerator):
    """String slots: values pass through verbatim, no length prefix
    (reference MultiSlotStringDataGenerator)."""

    def _format_sample(self, sample):
        parts = []
        for name, values in sample:
            parts.extend(str(v) for v in values)
        return ' '.join(parts)
