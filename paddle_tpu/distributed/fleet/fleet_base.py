"""Fleet singleton.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/base/fleet_base.py.
`fleet.init(strategy)` builds THE mesh: axes ordered (pp, dp, sp, tp)
— tp innermost so its heavy matmul-shard collectives ride adjacent ICI
links, pp outermost since its traffic is one activation handoff per
microbatch (see SURVEY §6).  Parameter-server paths (init_server etc.)
exist for API parity and run the TPU sharded-embedding substitute.
"""
import numpy as np

from .. import env as _env
from .distributed_strategy import DistributedStrategy

__all__ = ['init', 'get_fleet']


class HybridCommunicateGroup:
    """Reference: fleet/base/topology.py::HybridCommunicateGroup —
    answers "what is my rank/world-size along each parallel dimension".
    On TPU, ranks along axes are mesh coordinates; host code is rank 0
    of everything (one process drives all chips)."""

    def __init__(self, mesh):
        self._mesh = mesh
        shape = dict(mesh.shape) if mesh is not None else {}
        self._dp = shape.get('dp', 1)
        self._mp = shape.get('tp', 1)
        self._pp = shape.get('pp', 1)
        self._sp = shape.get('sp', 1)

    def get_data_parallel_world_size(self):
        return self._dp

    def get_model_parallel_world_size(self):
        return self._mp

    def get_pipe_parallel_world_size(self):
        return self._pp

    def get_sequence_parallel_world_size(self):
        return self._sp

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_model_parallel_group(self):
        from .. import collective
        return collective.new_group(axes=('tp',))

    def get_data_parallel_group(self):
        from .. import collective
        return collective.new_group(axes=('dp',))

    def get_pipe_parallel_group(self):
        from .. import collective
        return collective.new_group(axes=('pp',))

    def topology(self):
        return self._mesh


class Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._is_initialized = False
        self._role_maker = None
        self._util = None

    @property
    def util(self):
        """fleet.util (reference util_factory): host-side helpers."""
        if self._util is None:
            from .role_maker import UtilBase
            self._util = UtilBase(self._role_maker)
        return self._util

    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._role_maker = role_maker
        self._util = None   # rebuild fleet.util against this role maker
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        import jax
        n = jax.device_count()
        mp = max(1, hc.get('mp_degree') or 1)
        pp = max(1, hc.get('pp_degree') or 1)
        sp = max(1, hc.get('sp_degree') or 1)
        ep = max(1, hc.get('ep_degree') or 1)
        dp = hc.get('dp_degree') or -1
        if dp is None or dp <= 0:
            dp = max(1, n // (mp * pp * sp * ep))
        axes = [('pp', pp), ('dp', dp), ('sp', sp), ('ep', ep),
                ('tp', mp)]
        # only materialize axes that exist — 1-sized axes still get names
        # so PartitionSpecs stay valid regardless of strategy
        mesh = _env.build_mesh(axes)
        _env.set_mesh(mesh)
        self._hcg = HybridCommunicateGroup(mesh)
        self._is_initialized = True
        return self

    @property
    def strategy(self):
        return self._strategy


_fleet = Fleet()


def get_fleet():
    return _fleet


def init(role_maker=None, is_collective=True, strategy=None):
    return _fleet.init(role_maker, is_collective, strategy)


def get_hybrid_communicate_group():
    return _fleet._hcg


def distributed_model(model):
    """Reference wraps with DataParallel; under GSPMD the model is
    already mesh-aware via layer shardings — return as-is with the dp
    wrapper only for grad-sync API parity."""
    from ..parallel import DataParallel
    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    """Attach strategy-driven behavior to an optimizer.

    The reference chains meta_optimizers that rewrite the Program; here
    the strategy is carried on the optimizer and consumed by the
    compiled step builder (paddle_tpu.parallel.engine):
      lamb/lars → swap the update rule; sharding → shard opt state on dp;
      gradient_merge → scan-accumulate; recompute → remat policy.
    """
    strategy = strategy or _fleet._strategy or DistributedStrategy()
    validate_strategy(strategy)
    if strategy.lamb:
        from ...optimizer import Lamb
        if not isinstance(optimizer, Lamb):
            optimizer = Lamb(
                learning_rate=optimizer._learning_rate,  # live schedule
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                lamb_weight_decay=strategy.lamb_configs.get(
                    'lamb_weight_decay', 0.01))
    if strategy.dgc:
        # reference: meta_optimizers/dgc_optimizer.py — only applies to
        # Momentum; we swap in the semantics-equivalent DGCMomentum
        # (dense collective on ICI; see optimizer/dgc.py rationale)
        from ...optimizer import Momentum, DGCMomentum
        if isinstance(optimizer, Momentum):
            cfg = strategy.dgc_configs or {}
            # preserve the full original configuration: the live LR
            # schedule object (not a flattened float), weight decay,
            # grad clip, and nesterov all carry over
            optimizer = DGCMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                rampup_begin_step=cfg.get('rampup_begin_step', 0),
                rampup_step=cfg.get('rampup_step', 1),
                sparsity=cfg.get('sparsity', (0.999,)),
                use_nesterov=optimizer._nesterov,
                weight_decay=optimizer._coupled_wd or None,
                grad_clip=optimizer._grad_clip)
        else:
            import warnings
            warnings.warn(
                'strategy.dgc only applies to Momentum (reference '
                'dgc_optimizer.py raises for other optimizers); ignoring',
                UserWarning, stacklevel=2)
    optimizer._fleet_strategy = strategy
    return optimizer


def validate_strategy(strategy):
    """Reject or loudly flag strategy knobs that have no TPU behavior —
    a silently-inert perf flag is worse than an error (the reference
    either rewrites the Program or raises)."""
    import warnings
    if strategy is None:
        return
    if strategy.a_sync:
        warnings.warn(
            'strategy.a_sync: the dense (collective) path stays '
            'synchronous on TPU; asynchronous PS semantics exist for '
            'SPARSE tables via incubate.HostOffloadEmbedding (host-'
            'resident table, fire-and-forget host-side sparse update — '
            'reference: fleet/runtime/the_one_ps.py). Use it for the '
            'large-vocab embeddings that a_sync existed to serve.',
            UserWarning, stacklevel=2)
    if strategy.sharding:
        stage = strategy.sharding_configs.get('stage', 1)
        if stage not in (0, 1, 2):
            raise NotImplementedError(
                f'ZeRO sharding stage={stage}: stages 0/1/2 are '
                'implemented (opt-state + gradient sharding over dp); '
                'stage-3 parameter sharding is not yet')


# -- worker/server role API (parity; collective mode on TPU) -----------------

def is_first_worker():
    return _env.get_rank() == 0


def worker_index():
    return _env.get_rank()


def worker_num():
    import jax
    try:
        return jax.process_count()
    except RuntimeError:
        return 1


def is_worker():
    return True


def worker_endpoints(to_string=False):
    eps = _env.ParallelEnv().trainer_endpoints
    return ','.join(eps) if to_string else eps


def server_num():
    return 0


def server_index():
    return 0


def server_endpoints(to_string=False):
    return '' if to_string else []


def is_server():
    return False


def barrier_worker():
    from .. import collective
    collective.barrier()


def init_worker():
    pass


def init_server(*args, **kwargs):
    pass


def run_server():
    raise NotImplementedError(
        "there is no separate server process on TPU: the PS runtime is "
        "replaced by mesh-sharded embeddings (fleet VocabParallelEmbedding) "
        "for in-HBM tables and incubate.HostOffloadEmbedding (host-resident "
        "table + async host-side sparse update) for beyond-HBM vocabularies")


def stop_worker():
    pass
