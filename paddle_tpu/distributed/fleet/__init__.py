"""paddle_tpu.distributed.fleet — the distributed training facade.

Reference analogue: /root/reference/python/paddle/distributed/fleet/
(base/fleet_base.py Fleet singleton, DistributedStrategy proto,
meta_optimizers rewriting Programs, meta_parallel layers).  TPU-native:
a DistributedStrategy selects MESH AXES AND SHARDINGS, not graph
rewrites — `fleet.init` builds one jax.sharding.Mesh with axes
(pp, dp, sp, tp) sized from strategy.hybrid_configs, and the parallel
engine (paddle_tpu.parallel.engine) compiles the train step with
NamedShardings derived from layer metadata.  XLA then inserts the same
collectives the reference's meta_optimizers insert by hand (allreduce ≙
psum, ZeRO ≙ reduce-scatter + sharded opt state, etc.).
"""
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import (  # noqa: F401
    init, is_first_worker, worker_index, worker_num, is_worker,
    worker_endpoints, server_num, server_index, server_endpoints,
    is_server, barrier_worker, init_worker, init_server, run_server,
    stop_worker, distributed_optimizer, distributed_model, get_hybrid_communicate_group,
    get_fleet)
from .meta_parallel import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, PipelineLayer, LayerDesc, get_rng_state_tracker)
from .fleet_base import Fleet, HybridCommunicateGroup  # noqa: F401
from .topology import CommunicateTopology  # noqa: F401
from .role_maker import (  # noqa: F401
    Role, PaddleCloudRoleMaker, UserDefinedRoleMaker, UtilBase)
from .data_generator import (  # noqa: F401
    MultiSlotDataGenerator, MultiSlotStringDataGenerator)
from . import utils  # noqa: F401
from . import metrics  # noqa: F401


def __getattr__(name):
    # fleet.util tracks the CURRENT Fleet instance's role maker (a
    # plain import-time binding would freeze a pre-init UtilBase)
    if name == 'util':
        from .fleet_base import get_fleet
        return get_fleet().util
    raise AttributeError(name)

__all__ = ['DistributedStrategy', 'init', 'distributed_optimizer',
           'distributed_model', 'worker_index', 'worker_num',
           'is_first_worker', 'ColumnParallelLinear', 'RowParallelLinear',
           'VocabParallelEmbedding', 'ParallelCrossEntropy',
           'PipelineLayer', 'LayerDesc', 'get_hybrid_communicate_group',
           'Fleet', 'HybridCommunicateGroup', 'CommunicateTopology',
           'Role', 'PaddleCloudRoleMaker', 'UserDefinedRoleMaker',
           'UtilBase', 'MultiSlotDataGenerator',
           'MultiSlotStringDataGenerator', 'utils', 'util']
