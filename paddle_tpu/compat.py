"""paddle.compat — py2/3 string/number helpers of the fluid era.

Reference analogue: /root/reference/python/paddle/compat.py (to_text,
to_bytes, round, floor_division, get_exception_message).  Python-3-only
build: the py2 branches collapse.
"""
import math

__all__ = ['long_type', 'to_text', 'to_bytes', 'round',
           'floor_division', 'get_exception_message']

int_type = int
long_type = int


def _to_text(obj, encoding):
    if obj is None:
        return obj
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    if isinstance(obj, str):
        return obj
    return str(obj)


def to_text(obj, encoding='utf-8', inplace=False):
    """Convert str/bytes (or containers of them) to literal strings
    (reference compat.py::to_text)."""
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_to_text(i, encoding) for i in obj]
            return obj
        return [to_text(i, encoding) for i in obj]
    if isinstance(obj, set):
        if inplace:
            vals = [_to_text(i, encoding) for i in obj]
            obj.clear()
            obj.update(vals)
            return obj
        return {to_text(i, encoding) for i in obj}
    if isinstance(obj, dict):
        if inplace:
            for k in list(obj):
                obj[k] = to_text(obj[k], encoding)
            return obj
        return {k: to_text(v, encoding) for k, v in obj.items()}
    return _to_text(obj, encoding)


def _to_bytes(obj, encoding):
    if obj is None:
        return obj
    if isinstance(obj, str):
        return obj.encode(encoding)
    if isinstance(obj, bytes):
        return obj
    return str(obj).encode(encoding)


def to_bytes(obj, encoding='utf-8', inplace=False):
    """Convert str (or containers of str) to bytes (reference
    compat.py::to_bytes)."""
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_to_bytes(i, encoding) for i in obj]
            return obj
        return [to_bytes(i, encoding) for i in obj]
    if isinstance(obj, set):
        if inplace:
            vals = [_to_bytes(i, encoding) for i in obj]
            obj.clear()
            obj.update(vals)
            return obj
        return {to_bytes(i, encoding) for i in obj}
    if isinstance(obj, dict):
        if inplace:
            for k in list(obj):
                obj[k] = to_bytes(obj[k], encoding)
            return obj
        return {k: to_bytes(v, encoding) for k, v in obj.items()}
    return _to_bytes(obj, encoding)


def round(x, d=0):
    """Python-2-style round (half away from zero) — the reference keeps
    the py2 semantics for reproducibility (compat.py::round)."""
    if x == 0.0:
        return 0.0
    p = 10 ** d
    if x >= 0:
        return float(math.floor((x * p) + 0.5)) / p
    return float(math.ceil((x * p) - 0.5)) / p


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    """-> the exception's message string (reference
    compat.py::get_exception_message)."""
    return str(exc)
