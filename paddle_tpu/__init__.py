"""paddle_tpu — a TPU-native deep-learning framework with the API surface
of PaddlePaddle (reference: /root/reference, arogowie-intel/Paddle).

Compute path: JAX/XLA (+ Pallas kernels); parallelism: jax.sharding.Mesh
with pjit/shard_map; eager "dygraph" mode: tape over jax.vjp; compiled
mode: paddle_tpu.jit traces whole train steps into single XLA modules.
"""
__version__ = '0.1.0'

from .core import Tensor, no_grad, enable_grad, is_grad_enabled  # noqa: F401
from .core.tensor import Parameter  # noqa: F401
from .core.autograd import grad, set_grad_enabled  # noqa: F401
from .core.dtype import (  # noqa: F401
    float16, bfloat16, float32, float64, int8, int16, int32, int64, uint8,
    bool_, complex64, complex128, set_default_dtype, get_default_dtype,
    dtype)
from .core.dtype import bool_ as bool  # noqa: F401,A001
from .core.device import (  # noqa: F401
    CPUPlace, CUDAPlace, TPUPlace, XPUPlace, NPUPlace, CUDAPinnedPlace,
    set_device, get_device, device_count, is_compiled_with_cuda,
    is_compiled_with_xpu, is_compiled_with_npu, get_cudnn_version)
from .core.rng import seed  # noqa: F401
from .core.rng import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401
from .batch import batch  # noqa: F401

from .tensor import *  # noqa: F401,F403
from .tensor import __all__ as _tensor_all

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from .nn.layer.layers import ParamAttr  # noqa: F401

from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import framework  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .framework import ComplexTensor  # noqa: F401
from .static.program import enable_static, disable_static  # noqa: F401
from .static.program import in_static_mode as _in_static_mode
from .distributed.parallel import DataParallel  # noqa: F401


def in_dynamic_mode():
    """True unless paddle.enable_static() is active (reference
    fluid.framework.in_dygraph_mode, exported as in_dynamic_mode)."""
    return not _in_static_mode()
from . import distributed  # noqa: F401
from . import parallel  # noqa: F401
from . import vision  # noqa: F401
from . import text  # noqa: F401
from . import metric  # noqa: F401
from . import distribution  # noqa: F401
from . import hapi  # noqa: F401
from . import ops  # noqa: F401
from . import models  # noqa: F401
from . import analysis  # noqa: F401
from . import telemetry  # noqa: F401
from . import profiler  # noqa: F401
from . import utils  # noqa: F401
from . import resilience  # noqa: F401
from . import sysconfig  # noqa: F401
from . import autograd  # noqa: F401
from . import fluid  # noqa: F401
from . import hub  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import quantization  # noqa: F401
from . import compat  # noqa: F401
from . import device  # noqa: F401
from . import inference  # noqa: F401
from . import onnx  # noqa: F401
from . import incubate  # noqa: F401
from .hapi import Model, summary, flops  # noqa: F401
from . import callbacks  # noqa: F401

# --- 1.x/2.0 top-level compat tail (reference python/paddle/
# __init__.py:26-28,43,265-268) ---------------------------------------
# enable_dygraph/disable_dygraph are the names behind the reference's
# disable_static/enable_static aliases; dygraph is this framework's
# default mode, so they delegate to the static-mode switch.
from .fluid import enable_dygraph, disable_dygraph  # noqa: F401
from .fluid.framework import in_dygraph_mode  # noqa: F401
from .tensor.manipulation import crop as crop_tensor  # noqa: F401
# reference: `from .framework import VarBase as Tensor` — the 1.x name
# for the eager tensor is this framework's Tensor
VarBase = Tensor


def monkey_patch_variable():
    """Reference __init__ calls this to graft math methods onto static
    Variables (python/paddle/__init__.py:26,28).  Here static Program
    variables are built with their full method surface from the start
    (static/program.py), so the patch is an idempotent no-op kept for
    API parity."""


def monkey_patch_math_varbase():
    """Reference __init__ grafts math dunders onto VarBase
    (python/paddle/__init__.py:27,29).  Tensor ships with the full
    dunder surface (tensor/__init__.py binds 147 methods at import),
    so the patch is an idempotent no-op kept for API parity."""

__all__ = ['Tensor', 'Parameter', 'no_grad', 'enable_grad', 'seed',
           'set_device', 'get_device', 'save', 'load', 'enable_static',
           'disable_static', 'Model', 'summary', 'flops',
           'grad', 'set_grad_enabled', 'in_dynamic_mode', 'batch',
           'DataParallel', 'ComplexTensor', 'dtype', 'bool',
           'get_cuda_rng_state', 'set_cuda_rng_state',
           'NPUPlace', 'CUDAPinnedPlace', 'is_compiled_with_npu',
           'get_cudnn_version', 'enable_dygraph', 'disable_dygraph',
           'in_dygraph_mode', 'crop_tensor', 'VarBase'] + \
    list(_tensor_all)
