"""paddle_tpu.nn — layers, functionals, initializers, clipping.

Reference analogue: /root/reference/python/paddle/nn/.
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer.layers import Layer, ParamAttr  # noqa: F401
from .layer import *  # noqa: F401,F403
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)
from .utils import weight_norm, remove_weight_norm, spectral_norm  # noqa: F401
from .decode import Decoder, BeamSearchDecoder, dynamic_decode  # noqa: F401
