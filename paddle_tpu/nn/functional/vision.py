"""Spatial-transformer functionals: affine_grid + grid_sample.

Reference analogue: /root/reference/python/paddle/nn/functional/vision.py
(affine_grid_op / grid_sampler CUDA kernels).  TPU-native: the sampling
is 4 static gathers + bilinear weights — batched advanced indexing XLA
lowers to dynamic-gather, no scalar loops.
"""
import jax.numpy as jnp

from ...core.dispatch import apply
from ...tensor._helpers import wrap

__all__ = ['affine_grid', 'grid_sample']


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta: [N, 2, 3] affine matrices -> sampling grid
    [N, H, W, 2] in normalized [-1, 1] coords."""
    theta = wrap(theta)
    N, C, H, W = [int(s) for s in out_shape]

    def fn(t):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
        else:
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)        # [H, W, 3]
        return jnp.einsum('hwk,nck->nhwc', base.astype(t.dtype), t)

    return apply(fn, theta, op_name='affine_grid')


def grid_sample(x, grid, mode='bilinear', padding_mode='zeros',
                align_corners=True, name=None):
    """x: [N, C, H, W]; grid: [N, Ho, Wo, 2] in [-1, 1] (x, y).
    Returns [N, C, Ho, Wo]."""
    if mode not in ('bilinear', 'nearest'):
        raise ValueError(f'grid_sample: unsupported mode {mode!r}')
    if padding_mode not in ('zeros', 'border', 'reflection'):
        raise ValueError(
            f'grid_sample: unsupported padding_mode {padding_mode!r}')
    x, grid = wrap(x), wrap(grid)

    def unnorm(c, size):
        if align_corners:
            return (c + 1.0) / 2.0 * (size - 1)
        return ((c + 1.0) * size - 1.0) / 2.0

    def reflect(c, size):
        if align_corners:
            # reflect over the corner points: period 2*(size-1)
            span = 2.0 * (size - 1)
            if span == 0.0:
                return jnp.zeros_like(c)
            c = jnp.abs(jnp.mod(c, span))
            return jnp.where(c > (size - 1), span - c, c)
        # reflect over the pixel-AREA borders [-0.5, size-0.5]:
        # period 2*size, then clamp the half-pixel overshoot
        span = 2.0 * size
        c = jnp.mod(c + 0.5, span)
        c = jnp.where(c > size, span - c, c) - 0.5
        return jnp.clip(c, 0.0, size - 1)

    def fn(v, g):
        N, C, H, W = v.shape
        px = unnorm(g[..., 0].astype(jnp.float32), W)
        py = unnorm(g[..., 1].astype(jnp.float32), H)
        if padding_mode == 'reflection':
            px = reflect(px, W)
            py = reflect(py, H)

        def gather(yy, xx):
            yi = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
            out = v[jnp.arange(N)[:, None, None], :, yi, xi]
            if padding_mode == 'zeros':
                inb = ((yy >= 0) & (yy <= H - 1) & (xx >= 0)
                       & (xx <= W - 1)).astype(v.dtype)
                out = out * inb[..., None]
            return out                                   # [N,Ho,Wo,C]

        if mode == 'nearest':
            out = gather(jnp.round(py), jnp.round(px))
        else:
            y0, x0 = jnp.floor(py), jnp.floor(px)
            wy, wx = (py - y0)[..., None], (px - x0)[..., None]
            out = (gather(y0, x0) * (1 - wy) * (1 - wx)
                   + gather(y0, x0 + 1) * (1 - wy) * wx
                   + gather(y0 + 1, x0) * wy * (1 - wx)
                   + gather(y0 + 1, x0 + 1) * wy * wx)
        return jnp.moveaxis(out, -1, 1).astype(v.dtype)  # [N,C,Ho,Wo]

    return apply(fn, x, grid, op_name='grid_sample')
