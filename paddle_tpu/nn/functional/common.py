"""Common functionals: linear, dropout, embedding, padding, interpolation.

Reference analogue: /root/reference/python/paddle/nn/functional/common.py.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core import rng
from ...core.dispatch import apply
from ...tensor._helpers import wrap, raw

__all__ = [
    'linear', 'dropout', 'dropout2d', 'dropout3d', 'alpha_dropout',
    'embedding', 'embedding_prefix', 'one_hot', 'pad', 'interpolate',
    'upsample',
    'cosine_similarity', 'normalize', 'label_smooth', 'bilinear',
    'pixel_shuffle', 'unfold',
]


def linear(x, weight, bias=None, name=None):
    # paddle stores weight as [in, out] — direct MXU matmul, no transpose
    if bias is not None:
        return apply(lambda v, w, b: v @ w + b, wrap(x), wrap(weight),
                     wrap(bias), op_name='linear')
    return apply(lambda v, w: v @ w, wrap(x), wrap(weight), op_name='linear')


def dropout(x, p=0.5, axis=None, training=True, mode='upscale_in_train',
            name=None):
    x = wrap(x)
    if not training or p == 0.0:
        if mode == 'downscale_in_infer' and not training:
            return apply(lambda v: v * (1.0 - p), x, op_name='dropout')
        return x.clone()
    if p == 1.0:
        return apply(lambda v: v * 0.0, x, op_name='dropout')

    def fn(v):
        shape = list(v.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(rng.next_key(), 1.0 - p, tuple(shape))
        if mode == 'upscale_in_train':
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)

    return apply(fn, x, op_name='dropout')


def dropout2d(x, p=0.5, training=True, data_format='NCHW', name=None):
    ax = [0, 1] if data_format == 'NCHW' else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format='NCDHW', name=None):
    ax = [0, 1] if data_format == 'NCDHW' else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = wrap(x)
    if not training or p == 0.0:
        return x.clone()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(v):
        keep = jax.random.bernoulli(rng.next_key(), 1.0 - p, v.shape)
        a = (1.0 / np.sqrt((1.0 - p) * (1.0 + p * alpha_p ** 2)))
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return apply(fn, x, op_name='alpha_dropout')


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def fn(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply(fn, wrap(x), wrap(weight), op_name='embedding')


def embedding_prefix(weight, length):
    """First `length` rows of an embedding table — the training-path
    position-embedding lookup.  Equivalent to
    embedding(arange(length), weight) but a slice: its backward is a
    pad (dense) where the arange-gather's backward is a row scatter
    (HLO census, PERF.md round 4)."""
    return apply(lambda w: w[:length], wrap(weight),
                 op_name='embedding_prefix')


def one_hot(x, num_classes, name=None):
    return apply(lambda v: jax.nn.one_hot(v.astype(jnp.int32), num_classes),
                 wrap(x), op_name='one_hot')


def pad(x, pad, mode='constant', value=0.0, data_format='NCHW', name=None):
    x = wrap(x)
    pad = [int(raw(p)) for p in pad] if not isinstance(pad, int) else pad

    def fn(v):
        nd = v.ndim
        if isinstance(pad, int):
            cfg = [(pad, pad)] * nd
        elif len(pad) == 2 * nd:
            # paddle flat form: [d0_lo, d0_hi, d1_lo, ...]
            cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # spatial-only form, ordered last-dim-first pairs (torch style)
            cfg = [(0, 0)] * nd
            spatial_dims = list(range(nd - 1, 1, -1)) if data_format[1] == 'C' \
                else list(range(nd - 2, 0, -1))
            for i in range(len(pad) // 2):
                cfg[spatial_dims[i]] = (pad[2 * i], pad[2 * i + 1])
        jmode = {'constant': 'constant', 'reflect': 'reflect',
                 'replicate': 'edge', 'circular': 'wrap'}[mode]
        if jmode == 'constant':
            return jnp.pad(v, cfg, mode='constant', constant_values=value)
        return jnp.pad(v, cfg, mode=jmode)

    return apply(fn, x, op_name='pad')


def interpolate(x, size=None, scale_factor=None, mode='nearest',
                align_corners=False, align_mode=0, data_format='NCHW',
                name=None):
    x = wrap(x)
    channel_last = data_format in ('NHWC', 'NWC', 'NDHWC')
    nd = x.ndim
    n_sp = nd - 2
    sp_axes = list(range(1, 1 + n_sp)) if channel_last else \
        list(range(2, 2 + n_sp))
    in_sizes = [x.shape[a] for a in sp_axes]
    if size is not None:
        size = [int(raw(s)) for s in (size if isinstance(size, (list, tuple))
                                      else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
            [scale_factor] * n_sp
        size = [int(in_sizes[i] * float(sf[i])) for i in range(n_sp)]

    method = {'nearest': 'nearest', 'bilinear': 'linear',
              'trilinear': 'linear', 'linear': 'linear', 'bicubic': 'cubic',
              'area': 'linear'}[mode]

    def fn(v):
        out_shape = list(v.shape)
        for i, a in enumerate(sp_axes):
            out_shape[a] = size[i]
        if method == 'nearest':
            res = v
            for i, a in enumerate(sp_axes):
                idx = (jnp.arange(size[i]) * in_sizes[i] // size[i])
                res = jnp.take(res, idx, axis=a)
            return res
        return jax.image.resize(v, tuple(out_shape), method=method)

    return apply(fn, x, op_name='interpolate')


def upsample(x, size=None, scale_factor=None, mode='nearest',
             align_corners=False, align_mode=0, data_format='NCHW',
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)
    return apply(fn, wrap(x1), wrap(x2), op_name='cosine_similarity')


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        if p == 2:
            n = jnp.linalg.norm(v, axis=axis, keepdims=True)
        else:
            n = jnp.sum(jnp.abs(v) ** p, axis=axis,
                        keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)
    return apply(fn, wrap(x), op_name='normalize')


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(v):
        k = v.shape[-1]
        if prior_dist is not None:
            pd = raw(prior_dist)
            return (1 - epsilon) * v + epsilon * pd
        return (1 - epsilon) * v + epsilon / k
    return apply(fn, wrap(label), op_name='label_smooth')


def bilinear(x1, x2, weight, bias=None, name=None):
    # weight: [out, in1, in2]
    def fn(a, b, w, *maybe_bias):
        out = jnp.einsum('bi,oij,bj->bo', a, w, b)
        if maybe_bias:
            out = out + maybe_bias[0]
        return out
    if bias is not None:
        return apply(fn, wrap(x1), wrap(x2), wrap(weight), wrap(bias),
                     op_name='bilinear')
    return apply(fn, wrap(x1), wrap(x2), wrap(weight), op_name='bilinear')


def pixel_shuffle(x, upscale_factor, data_format='NCHW', name=None):
    r = int(upscale_factor)

    def fn(v):
        if data_format == 'NCHW':
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))

    return apply(fn, wrap(x), op_name='pixel_shuffle')


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import _tuple
    ks = _tuple(kernel_sizes, 2)
    st = _tuple(strides, 2)
    pd = _tuple(paddings, 2)
    dl = _tuple(dilations, 2)

    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])])
        oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                ii, jj = i * dl[0], j * dl[1]
                patches.append(v[:, :, ii:ii + oh * st[0]:st[0],
                                 jj:jj + ow * st[1]:st[1]])
        out = jnp.stack(patches, axis=2)  # [n, c, k*k, oh, ow]
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)

    return apply(fn, wrap(x), op_name='unfold')


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched diagonal embedding (reference: nn/functional/extension.py
    ::diag_embed): input [..., N] -> output with N placed on the
    (dim1, dim2) diagonal at `offset`."""
    x = wrap(input)

    def fn(v):
        n = v.shape[-1]
        size = n + abs(int(offset))
        out = jnp.zeros(v.shape[:-1] + (size, size), v.dtype)
        i = jnp.arange(n)
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        out = out.at[..., r, c].set(v)
        d1 = dim1 % out.ndim
        d2 = dim2 % out.ndim
        if (d1, d2) != (out.ndim - 2, out.ndim - 1):
            perm = [i for i in range(out.ndim) if i not in
                    (out.ndim - 2, out.ndim - 1)]
            # move the two diagonal dims to the requested positions
            order = perm.copy()
            for pos, ax in sorted([(d1, out.ndim - 2),
                                   (d2, out.ndim - 1)]):
                order.insert(pos, ax)
            out = jnp.transpose(out, order)
        return out

    return apply(fn, x, op_name='diag_embed')


__all__ += ['diag_embed']


def sequence_mask(x, maxlen=None, dtype='int64', name=None):
    """y[..., j] = j < x[...] (reference fluid/layers/sequence_lod.py
    sequence_mask).  maxlen defaults to max(x), which requires a
    concrete eager value — under jit/to_static/static Programs the mask
    shape must be static, so pass maxlen explicitly there.  The static
    sequence_* ops' 2-D mask (static/sequence.py) delegates here."""
    from ...core.dtype import convert_dtype
    from ...tensor._helpers import napply
    x = wrap(x)
    if maxlen is None:
        try:
            v = x.value
        except RuntimeError:
            v = None  # static-Program Variable: no build-time value
        if v is None or isinstance(v, jax.core.Tracer):
            raise ValueError(
                'sequence_mask(maxlen=None) needs a concrete x; under '
                'jit/to_static/static Programs the mask shape must be '
                'static — pass maxlen explicitly')
        maxlen = int(np.asarray(jax.device_get(v)).max())
    maxlen = int(maxlen)
    d = convert_dtype(dtype)

    def fn(v):
        j = jnp.arange(maxlen)
        return (j < v[..., None]).astype(d)
    return napply(fn, x, op_name='sequence_mask')


def gather_tree(ids, parents):
    """Backtrace beam-search ids along parents (reference
    fluid/layers/nn.py gather_tree; paddle.nn.functional.gather_tree).

    ids, parents: [max_time, batch, beam] int.  Walks from the last step
    backwards via a lax.scan (static trip count — compiles to one fused
    loop on TPU) re-selecting each step's token by the surviving beam.
    """
    from ...tensor._helpers import napply

    def fn(idv, parv):
        T, B, K = idv.shape
        init = jnp.tile(jnp.arange(K, dtype=parv.dtype)[None, :], (B, 1))

        def body(beams, t):
            tok = jnp.take_along_axis(idv[t], beams, axis=-1)
            nxt = jnp.take_along_axis(parv[t], beams, axis=-1)
            return nxt, tok
        _, toks = jax.lax.scan(body, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]
    return napply(fn, wrap(ids), wrap(parents), op_name='gather_tree')


__all__ += ['sequence_mask', 'gather_tree']
