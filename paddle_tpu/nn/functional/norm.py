"""Normalization functionals.

Reference analogue: /root/reference/python/paddle/nn/functional/norm.py
(cuDNN batch-norm kernels).  TPU-native: plain jnp reductions — XLA fuses
mean/var/normalize into one or two HBM passes; the Pallas fused layer_norm
in paddle_tpu.ops.pallas is substituted on TPU for the hot path.
"""
import jax.numpy as jnp

from ...core.dispatch import apply
from ...tensor._helpers import wrap

__all__ = ['batch_norm', 'layer_norm', 'instance_norm', 'group_norm',
           'local_response_norm']


def _one_pass_var(v, axes, mean, keepdims=False):
    """E[x²]−E[x]² with f32 accumulation, clamped ≥ 0 (the one-pass
    form can go slightly negative from f32 cancellation when
    var ≪ mean², which would NaN the sqrt).

    For bf16 the square stays in bf16 — f32 exponent range, and an f32
    upcast before the square would make autodiff save an f32 copy of
    the activations for the square's VJP.  fp16 squares overflow at
    |x| ≥ 256, so non-bf16 dtypes upcast first."""
    f32 = jnp.float32
    sq = jnp.square(v) if v.dtype == jnp.bfloat16 \
        else jnp.square(v.astype(f32))
    var = jnp.mean(sq, axis=axes, dtype=f32,
                   keepdims=keepdims) - jnp.square(mean)
    return jnp.maximum(var, 0.0)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format='NCHW', use_global_stats=None, name=None):
    """Returns the normalized tensor; updates running stats in-place on the
    passed Tensors when training (eager semantics, like the reference)."""
    x = wrap(x)
    channel_last = data_format in ('NHWC', 'NLC', 'NDHWC')
    ch_axis = x.ndim - 1 if channel_last else min(1, x.ndim - 1)
    red_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    if use_batch_stats:
        def fn(v, w, b):
            # Mixed-precision contract (TPU): statistics accumulate in
            # float32 regardless of v.dtype, but the normalization is
            # applied in v.dtype as a folded per-channel scale/shift —
            # two elementwise ops XLA fuses into the producing conv's
            # epilogue.  Upcasting v here would double the HBM bytes of
            # every activation saved for backward (bandwidth-bound).
            f32 = jnp.float32
            mean = jnp.mean(v, axis=red_axes, dtype=f32)
            if v.dtype == f32:
                var = jnp.var(v, axis=red_axes)
            else:
                var = _one_pass_var(v, red_axes, mean)
            inv = 1.0 / jnp.sqrt(var + epsilon)
            scale = inv if w is None else inv * w.astype(f32)
            shift = -mean * scale
            if b is not None:
                shift = shift + b.astype(f32)
            out = (v * scale.reshape(shape).astype(v.dtype)
                   + shift.reshape(shape).astype(v.dtype))
            return out, mean, var

        args = [x]
        w_t = wrap(weight) if weight is not None else None
        b_t = wrap(bias) if bias is not None else None

        def fn2(v, *wb):
            w = wb[0] if w_t is not None else None
            b = wb[-1] if b_t is not None else None
            return fn(v, w, b)

        ins = [t for t in (x, w_t, b_t) if t is not None]
        out, mean, var = apply(fn2, *ins, op_name='batch_norm')
        # running-stat update (paddle: moving average with momentum);
        # expressed as dispatched Tensor ops so it records symbolically
        # in static mode and traces correctly under jit
        from ...core.autograd import no_grad
        with no_grad():
            if running_mean is not None:
                running_mean.set_value(running_mean * momentum +
                                       mean.detach() * (1.0 - momentum))
            if running_var is not None:
                n = 1
                for i in red_axes:
                    n *= x.shape[i]
                unbiased = var.detach() * (n / max(n - 1, 1))
                running_var.set_value(running_var * momentum +
                                      unbiased * (1.0 - momentum))
        return out

    rm, rv = wrap(running_mean), wrap(running_var)

    def fn_eval(v, m, s, *wb):
        f32 = jnp.float32
        inv = 1.0 / jnp.sqrt(s.astype(f32) + epsilon)
        i = 0
        scale = inv
        if weight is not None:
            scale = inv * wb[i].astype(f32)
            i += 1
        shift = -m.astype(f32) * scale
        if bias is not None:
            shift = shift + wb[i].astype(f32)
        return (v * scale.reshape(shape).astype(v.dtype)
                + shift.reshape(shape).astype(v.dtype))

    ins = [x, rm, rv]
    if weight is not None:
        ins.append(wrap(weight))
    if bias is not None:
        ins.append(wrap(bias))
    return apply(fn_eval, *ins, op_name='batch_norm')


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    x = wrap(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))

    def fn(v, *wb):
        if n_axes == 1 and weight is not None and bias is not None:
            # Pallas-fused on TPU (falls back to jnp off-TPU / odd shapes)
            from ...ops import fused_layer_norm
            return fused_layer_norm(v, wb[0], wb[1], eps=epsilon)
        f32 = jnp.float32
        mean = jnp.mean(v, axis=axes, keepdims=True, dtype=f32)
        if v.dtype == f32:
            var = jnp.var(v, axis=axes, keepdims=True)
        else:
            var = _one_pass_var(v, axes, mean, keepdims=True)
        inv = (1.0 / jnp.sqrt(var + epsilon)).astype(v.dtype)
        out = (v - mean.astype(v.dtype)) * inv
        i = 0
        if weight is not None:
            out = out * wb[i].astype(v.dtype)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(v.dtype)
        return out

    ins = [x]
    if weight is not None:
        ins.append(wrap(weight))
    if bias is not None:
        ins.append(wrap(bias))
    return apply(fn, *ins, op_name='layer_norm')


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  eps=1e-5, data_format='NCHW', name=None):
    x = wrap(x)
    channel_last = data_format in ('NHWC', 'NLC', 'NDHWC')
    ch_axis = x.ndim - 1 if channel_last else 1
    red_axes = tuple(i for i in range(2, x.ndim)) if not channel_last else \
        tuple(i for i in range(1, x.ndim - 1))
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    def fn(v, *wb):
        f32 = jnp.float32
        mean = jnp.mean(v, axis=red_axes, keepdims=True, dtype=f32)
        if v.dtype == f32:
            var = jnp.var(v, axis=red_axes, keepdims=True)
        else:
            var = _one_pass_var(v, red_axes, mean, keepdims=True)
        # fold into per-(sample,channel) scale/shift applied in v.dtype
        scale = 1.0 / jnp.sqrt(var + eps)
        shift = -mean * scale
        i = 0
        if weight is not None:
            scale = scale * wb[i].reshape(shape).astype(f32)
            shift = shift * wb[i].reshape(shape).astype(f32)
            i += 1
        if bias is not None:
            shift = shift + wb[i].reshape(shape).astype(f32)
        return v * scale.astype(v.dtype) + shift.astype(v.dtype)

    ins = [x]
    if weight is not None:
        ins.append(wrap(weight))
    if bias is not None:
        ins.append(wrap(bias))
    return apply(fn, *ins, op_name='instance_norm')


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format='NCHW', name=None):
    x = wrap(x)
    channel_last = data_format in ('NHWC', 'NLC', 'NDHWC')

    def fn(v, *wb):
        if channel_last:
            v_t = jnp.moveaxis(v, -1, 1)
        else:
            v_t = v
        n, c = v_t.shape[0], v_t.shape[1]
        g = num_groups
        grouped = v_t.reshape((n, g, c // g) + v_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        f32 = jnp.float32
        mean = jnp.mean(grouped, axis=axes, keepdims=True, dtype=f32)
        if grouped.dtype == f32:
            var = jnp.var(grouped, axis=axes, keepdims=True)
        else:
            var = _one_pass_var(grouped, axes, mean, keepdims=True)
        inv = (1.0 / jnp.sqrt(var + epsilon))
        out = ((grouped - mean.astype(grouped.dtype))
               * inv.astype(grouped.dtype)).reshape(v_t.shape)
        shape = [1] * v_t.ndim
        shape[1] = c
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    ins = [x]
    if weight is not None:
        ins.append(wrap(weight))
    if bias is not None:
        ins.append(wrap(bias))
    return apply(fn, *ins, op_name='group_norm')


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format='NCHW', name=None):
    x = wrap(x)
    channel_last = data_format in ('NHWC', 'NLC', 'NDHWC')
    ch_axis = x.ndim - 1 if channel_last else 1

    def fn(v):
        sq = jnp.square(v)
        half = size // 2
        pads = [(0, 0)] * v.ndim
        pads[ch_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(v)
        for i in range(size):
            sl = [slice(None)] * v.ndim
            sl[ch_axis] = slice(i, i + v.shape[ch_axis])
            acc = acc + padded[tuple(sl)]
        return v / jnp.power(k + alpha * acc, beta)

    return apply(fn, x, op_name='local_response_norm')
