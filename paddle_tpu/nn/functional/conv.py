"""Convolution functionals.

Reference analogue: /root/reference/python/paddle/nn/functional/conv.py
(cuDNN kernels).  TPU-native: one lax.conv_general_dilated call; XLA's
TPU backend picks MXU-friendly layouts internally, so we keep paddle's
NCHW/OIHW API contract without a performance penalty.
"""
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import apply
from ...tensor._helpers import wrap

__all__ = ['conv1d', 'conv2d', 'conv3d', 'conv1d_transpose',
           'conv2d_transpose', 'conv3d_transpose']


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else list(v) * n))
    return (int(v),) * n


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and not isinstance(padding[0], (list, tuple)):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                for i in range(n)]
    return [tuple(int(q) for q in p) for p in padding]


def _conv(x, w, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = data_format in ('NHWC', 'NLC', 'NDHWC')
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    pad = _padding(padding, n)
    sp = 'DHW'[-n:]
    dn = (f"N{sp}C", f"OI{sp}", f"N{sp}C") if channel_last else \
        (f"NC{sp}", f"OI{sp}", f"NC{sp}")

    def fn(v, k, *maybe_b):
        out = lax.conv_general_dilated(
            v, k, window_strides=stride, padding=pad,
            rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply(fn, wrap(x), wrap(w), wrap(bias), op_name=f'conv{n}d')
    return apply(fn, wrap(x), wrap(w), op_name=f'conv{n}d')


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCL', name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCHW', name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCDHW', name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format)


def _conv_transpose(x, w, bias, stride, padding, output_padding, groups,
                    dilation, n, data_format, output_size=None):
    channel_last = data_format in ('NHWC', 'NLC', 'NDHWC')
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    pad = _padding(padding, n)
    sp = 'DHW'[-n:]
    dn = (f"N{sp}C", f"OI{sp}", f"N{sp}C") if channel_last else \
        (f"NC{sp}", f"OI{sp}", f"NC{sp}")
    opad = _tuple(output_padding, n) if output_padding else (0,) * n
    if output_size is not None:
        # several input sizes map to one transposed-conv output; the
        # caller disambiguates by requesting the exact size, realized
        # as extra one-sided output padding over the minimal size
        output_size = _tuple(output_size, n)
        ww = wrap(w)
        ksz_w = [ww.shape[2 + i] for i in range(n)]
        in_sp = [wrap(x).shape[1 + i if channel_last else 2 + i]
                 for i in range(n)]
        pad0 = _padding(padding, n)
        opad = []
        for i in range(n):
            kd = (ksz_w[i] - 1) * dilation[i]
            base = ((in_sp[i] - 1) * stride[i] - pad0[i][0]
                    - pad0[i][1] + kd + 1)
            extra = int(output_size[i]) - base
            if not 0 <= extra < max(stride[i], 1):
                raise ValueError(
                    f'output_size[{i}]={output_size[i]} not reachable '
                    f'from input size {in_sp[i]} (minimal {base})')
            opad.append(extra)
        opad = tuple(opad)

    def fn(v, k, *maybe_b):
        # paddle transpose-kernel layout: [in_c, out_c/groups, *sp].
        # Express the transpose as a regular conv over an lhs-dilated
        # input with a spatially-flipped, in/out-swapped kernel.
        ax = tuple(range(2, 2 + n))
        k2 = jnp.swapaxes(jnp.flip(k, axis=ax), 0, 1)  # [oc/g, in_c, *sp]
        if groups > 1:
            oc_g, ic = k2.shape[0], k2.shape[1]
            k2 = k2.reshape((oc_g, groups, ic // groups) + k2.shape[2:])
            k2 = jnp.moveaxis(k2, 1, 0).reshape(
                (groups * oc_g, ic // groups) + k2.shape[3:])
        ksz = [k.shape[2 + i] for i in range(n)]
        if isinstance(pad, str):
            base = [(0, 0)] * n if pad == 'VALID' else [
                ((ksz[i] - 1) // 2, (ksz[i] - 1) // 2) for i in range(n)]
        else:
            base = pad
        tpad = []
        for i in range(n):
            kd = (ksz[i] - 1) * dilation[i]
            tpad.append((kd - base[i][0], kd - base[i][1] + opad[i]))
        out = lax.conv_general_dilated(
            v, k2, window_strides=(1,) * n, padding=tpad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply(fn, wrap(x), wrap(w), wrap(bias),
                     op_name=f'conv{n}d_transpose')
    return apply(fn, wrap(x), wrap(w), op_name=f'conv{n}d_transpose')


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format='NCL', name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 1, data_format,
                           output_size=output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format='NCHW', name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 2, data_format,
                           output_size=output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format='NCDHW', name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 3, data_format,
                           output_size=output_size)
