from .activation import *    # noqa: F401,F403
from .common import *        # noqa: F401,F403
from .conv import *          # noqa: F401,F403
from .norm import *          # noqa: F401,F403
from .pooling import *       # noqa: F401,F403
from .loss import *          # noqa: F401,F403
from .vision import *        # noqa: F401,F403

from . import (activation, common, conv, norm, pooling, loss,  # noqa: F401
               vision)

from ...tensor.math import tanh_  # noqa: F401  (in-place functional alias)

__all__ = (activation.__all__ + common.__all__ + conv.__all__ +
           norm.__all__ + pooling.__all__ + loss.__all__ +
           vision.__all__ + ['tanh_'])
