"""Activation functionals.

Reference analogue: /root/reference/python/paddle/nn/functional/activation.py.
All are jnp/jax.nn lambdas through the dispatch tape; XLA fuses them into
surrounding matmuls so there is no reason for the reference's fused
activation kernels.
"""
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...tensor._helpers import wrap

__all__ = [
    'relu', 'relu6', 'relu_', 'gelu', 'sigmoid', 'softmax', 'log_softmax',
    'tanh', 'leaky_relu', 'elu', 'selu', 'celu', 'hardswish', 'hardsigmoid',
    'swish', 'silu', 'mish', 'softplus', 'softsign', 'hardtanh',
    'hardshrink', 'softshrink', 'tanhshrink', 'prelu', 'glu', 'maxout',
    'thresholded_relu', 'log_sigmoid', 'gumbel_softmax',
]


def relu(x, name=None):
    return apply(jax.nn.relu, wrap(x), op_name='relu')


def relu_(x, name=None):
    x._replace(apply(jax.nn.relu, x._snapshot(), op_name='relu'))
    return x


def relu6(x, name=None):
    return apply(jax.nn.relu6, wrap(x), op_name='relu6')


def gelu(x, approximate=False, name=None):
    return apply(lambda v: jax.nn.gelu(v, approximate=approximate), wrap(x),
                 op_name='gelu')


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, wrap(x), op_name='sigmoid')


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, wrap(x), op_name='log_sigmoid')


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if axis in (-1, v.ndim - 1):
            from ...ops import fused_softmax
            return fused_softmax(v)  # Pallas on TPU, jnp fallback
        return jax.nn.softmax(v, axis=axis)
    return apply(fn, wrap(x), op_name='softmax')


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply(lambda v: jax.nn.log_softmax(v, axis=axis), wrap(x),
                 op_name='log_softmax')


def tanh(x, name=None):
    return apply(jnp.tanh, wrap(x), op_name='tanh')


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda v: jax.nn.leaky_relu(v, negative_slope), wrap(x),
                 op_name='leaky_relu')


def elu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.elu(v, alpha), wrap(x), op_name='elu')


def selu(x,
         scale=1.0507009873554804934193349852946,
         alpha=1.6732632423543772848170429916717, name=None):
    return apply(lambda v: scale * jnp.where(
        v > 0, v, alpha * jnp.expm1(v)), wrap(x), op_name='selu')


def celu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.celu(v, alpha), wrap(x), op_name='celu')


def hardswish(x, name=None):
    return apply(jax.nn.hard_swish, wrap(x), op_name='hardswish')


def hardsigmoid(x, slope=1.0 / 6, offset=0.5, name=None):
    return apply(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), wrap(x),
                 op_name='hardsigmoid')


def swish(x, name=None):
    return apply(jax.nn.silu, wrap(x), op_name='swish')


silu = swish


def mish(x, name=None):
    return apply(lambda v: v * jnp.tanh(jax.nn.softplus(v)), wrap(x),
                 op_name='mish')


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda v: jnp.where(
        beta * v > threshold, v, (1.0 / beta) * jax.nn.softplus(beta * v)),
        wrap(x), op_name='softplus')


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, wrap(x), op_name='softsign')


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda v: jnp.clip(v, min, max), wrap(x),
                 op_name='hardtanh')


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0),
                 wrap(x), op_name='hardshrink')


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(
        v > threshold, v - threshold,
        jnp.where(v < -threshold, v + threshold, 0.0)), wrap(x),
        op_name='softshrink')


def tanhshrink(x, name=None):
    return apply(lambda v: v - jnp.tanh(v), wrap(x), op_name='tanhshrink')


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(lambda v: jnp.where(v > threshold, v, 0.0), wrap(x),
                 op_name='thresholded_relu')


def prelu(x, weight, data_format='NCHW', name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v >= 0, v, w.reshape(()) * v)
        ch_axis = 1 if data_format == 'NCHW' else v.ndim - 1
        shape = [1] * v.ndim
        shape[ch_axis] = w.size
        return jnp.where(v >= 0, v, w.reshape(shape) * v)
    return apply(fn, wrap(x), wrap(weight), op_name='prelu')


def glu(x, axis=-1, name=None):
    def fn(v):
        a, b = jnp.split(v, 2, axis=axis)
        return a * jax.nn.sigmoid(b)
    return apply(fn, wrap(x), op_name='glu')


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return apply(fn, wrap(x), op_name='maxout')


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import rng
    def fn(v):
        g = jax.random.gumbel(rng.next_key(), v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return apply(fn, wrap(x), op_name='gumbel_softmax')


# in-place variants (reference activation.py: elu_/softmax_ mutate but
# keep the tape edge via the _snapshot/_replace pattern)

def elu_(x, alpha=1.0, name=None):
    x._replace(elu(x._snapshot(), alpha=alpha))
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    x._replace(softmax(x._snapshot(), axis=axis, dtype=dtype))
    return x


__all__ += ['elu_', 'softmax_']
