"""Pooling functionals via lax.reduce_window.

Reference analogue: /root/reference/python/paddle/nn/functional/pooling.py.
"""
import numpy as np
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import apply
from ...tensor._helpers import wrap
from .conv import _tuple, _padding

__all__ = [
    'avg_pool1d', 'avg_pool2d', 'avg_pool3d', 'max_pool1d', 'max_pool2d',
    'max_pool3d', 'adaptive_avg_pool1d', 'adaptive_avg_pool2d',
    'adaptive_avg_pool3d', 'adaptive_max_pool1d', 'adaptive_max_pool2d',
    'adaptive_max_pool3d',
]


def _pool(x, ksize, stride, padding, n, data_format, kind, exclusive=True,
          ceil_mode=False):
    channel_last = data_format in ('NHWC', 'NLC', 'NDHWC')
    ksize = _tuple(ksize, n)
    stride = _tuple(stride if stride is not None else ksize, n)
    pad = _padding(padding, n)
    if isinstance(pad, str):
        pad = [(0, 0)] * n if pad == 'VALID' else None  # None → SAME later
    sp_axes = tuple(range(1, 1 + n)) if channel_last else \
        tuple(range(2, 2 + n))

    if ceil_mode and pad is not None:
        # extend high padding so partial windows are kept; reduce_window
        # pads with the reduction's init value (-inf for max, 0 for add),
        # and the exclusive-avg count window sees the same pads, so the
        # divisor stays correct.
        x_shape = list(wrap(x).shape)
        pad = list(pad)
        for i, ax in enumerate(sp_axes):
            size = x_shape[ax] + pad[i][0] + pad[i][1]
            rem = (size - ksize[i]) % stride[i]
            if rem:
                pad[i] = (pad[i][0], pad[i][1] + stride[i] - rem)

    def expand(vals, one):
        full = [one] * (n + 2)
        for i, ax in enumerate(sp_axes):
            full[ax] = vals[i]
        return tuple(full)

    window = expand(ksize, 1)
    strides = expand(stride, 1)
    if pad is None:
        pads = 'SAME'
    else:
        pads = expand(pad, (0, 0))

    def fn(v):
        if kind == 'max':
            init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else \
                jnp.iinfo(v.dtype).min
            return lax.reduce_window(v, init, lax.max, window, strides,
                                     pads)
        s = lax.reduce_window(v, 0.0, lax.add, window, strides, pads)
        if exclusive:
            ones = jnp.ones_like(v)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                    pads)
            return s / cnt
        return s / float(np.prod(ksize))

    return apply(fn, wrap(x), op_name=f'{kind}_pool{n}d')


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, 'NCL', 'avg', exclusive,
                 ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCHW',
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, 'avg',
                 exclusive, ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCDHW',
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, 'avg',
                 exclusive, ceil_mode)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, 'NCL', 'max',
                 ceil_mode=ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCHW', name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, 'max',
                 ceil_mode=ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCDHW', name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, 'max',
                 ceil_mode=ceil_mode)


def _adaptive(x, output_size, n, kind, data_format):
    channel_last = data_format in ('NHWC', 'NLC', 'NDHWC')
    out = _tuple(output_size, n)
    sp_axes = tuple(range(1, 1 + n)) if channel_last else \
        tuple(range(2, 2 + n))

    def fn(v):
        res = v
        # adaptive pooling = split each spatial dim into output_size bins;
        # when divisible this is a plain reduce_window (the common case)
        for i, ax in enumerate(sp_axes):
            size = res.shape[ax]
            if out[i] == 1:
                res = (jnp.max if kind == 'max' else jnp.mean)(
                    res, axis=ax, keepdims=True)
            elif size % out[i] == 0:
                k = size // out[i]
                shp = res.shape[:ax] + (out[i], k) + res.shape[ax + 1:]
                res = (jnp.max if kind == 'max' else jnp.mean)(
                    res.reshape(shp), axis=ax + 1)
            else:
                # uneven bins: gather-based windows (rare path)
                starts = [int(np.floor(j * size / out[i]))
                          for j in range(out[i])]
                ends = [int(np.ceil((j + 1) * size / out[i]))
                        for j in range(out[i])]
                chunks = []
                for s_, e_ in zip(starts, ends):
                    sl = [np.s_[:]] * res.ndim
                    sl[ax] = np.s_[s_:e_]
                    red = (jnp.max if kind == 'max' else jnp.mean)(
                        res[tuple(sl)], axis=ax, keepdims=True)
                    chunks.append(red)
                res = jnp.concatenate(chunks, axis=ax)
        return res

    return apply(fn, wrap(x), op_name=f'adaptive_{kind}_pool{n}d')


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, 'avg', 'NCL')


def adaptive_avg_pool2d(x, output_size, data_format='NCHW', name=None):
    return _adaptive(x, output_size, 2, 'avg', data_format)


def adaptive_avg_pool3d(x, output_size, data_format='NCDHW', name=None):
    return _adaptive(x, output_size, 3, 'avg', data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, 'max', 'NCL')


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, 'max', 'NCHW')


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, 'max', 'NCDHW')
