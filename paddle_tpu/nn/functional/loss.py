"""Loss functionals.

Reference analogue: /root/reference/python/paddle/nn/functional/loss.py
(softmax_with_cross_entropy fused kernel etc.).  TPU-native: fused
log_softmax+gather formulation; XLA keeps it one kernel.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...tensor._helpers import wrap

__all__ = [
    'cross_entropy', 'softmax_with_cross_entropy', 'binary_cross_entropy',
    'binary_cross_entropy_with_logits', 'mse_loss', 'l1_loss', 'nll_loss',
    'kl_div', 'smooth_l1_loss', 'margin_ranking_loss', 'ctc_loss',
    'hinge_embedding_loss', 'cosine_embedding_loss', 'square_error_cost',
    'sigmoid_focal_loss', 'log_loss',
]


def _reduce(v, reduction):
    if reduction == 'mean':
        return jnp.mean(v)
    if reduction == 'sum':
        return jnp.sum(v)
    return v


@jax.custom_vjp
def _softmax_nll(x, lab):
    """Per-token -log_softmax(x)[lab] over the LAST axis.

    The autodiff backward of the take_along_axis gather is a
    scatter-add into the full [N, V] buffer — serialized on TPU; the
    unfused GPT-2 train step measured ~8x slower than expected at
    vocab shape [8192, 50304] with it on the path (PERF.md round-4
    chip session 2; tools/bench_ce_backward.py isolates the
    formulations on hardware).  The custom backward emits the
    classic softmax-CE gradient (softmax - one_hot) * g as dense
    elementwise math, and recomputes softmax from the saved logits
    instead of keeping the f32 log-probs residual alive.
    """
    logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]


def _softmax_nll_fwd(x, lab):
    xf = x.astype(jnp.float32)
    lse = jax.nn.logsumexp(xf, axis=-1, keepdims=True)
    picked = jnp.take_along_axis(xf, lab[..., None], axis=-1)
    return (lse - picked)[..., 0], (x, lab, lse)


def _softmax_nll_bwd(res, g):
    x, lab, lse = res
    p = jnp.exp(x.astype(jnp.float32) - lse)
    oh = lab[..., None] == jnp.arange(x.shape[-1], dtype=lab.dtype)
    dx = (p - oh.astype(p.dtype)) * g[..., None]
    return dx.astype(x.dtype), np.zeros(np.shape(lab), jax.dtypes.float0)


_softmax_nll.defvjp(_softmax_nll_fwd, _softmax_nll_bwd)


@jax.custom_vjp
def _pick_nll(logp, lab):
    """-logp[..., lab] over the last axis, with a dense -one_hot*g
    backward (the autodiff gather backward is a serialized scatter on
    TPU, same pathology as _softmax_nll)."""
    return -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]


def _pick_nll_fwd(logp, lab):
    # residual carries class count + dtype as a [C]-zeros template
    # (custom_vjp residuals must be arrays, not dtype objects)
    tmpl = jnp.zeros((logp.shape[-1],), logp.dtype)
    return _pick_nll(logp, lab), (lab, tmpl)


def _pick_nll_bwd(res, g):
    lab, tmpl = res
    oh = lab[..., None] == jnp.arange(tmpl.shape[0], dtype=lab.dtype)
    dlogp = jnp.where(oh, -g[..., None], 0.0).astype(tmpl.dtype)
    return dlogp, np.zeros(np.shape(lab), jax.dtypes.float0)


_pick_nll.defvjp(_pick_nll_fwd, _pick_nll_bwd)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction='mean', soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    ins = [wrap(input), wrap(label)]
    if weight is not None:
        ins.append(wrap(weight))

    def fn(logits, lab, *maybe_w):
        if soft_label:
            if use_softmax:
                logp = jax.nn.log_softmax(logits, axis=axis)
            else:
                logp = jnp.log(jnp.maximum(logits, 1e-30))
            per = -jnp.sum(lab * logp, axis=axis)
            if maybe_w:
                per = per * jnp.sum(lab * maybe_w[0], axis=axis)
            return _reduce(per, reduction)
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logits.ndim:
            lab_i = jnp.squeeze(lab_i, axis=axis)
        safe = jnp.where(lab_i == ignore_index, 0, lab_i)
        if use_softmax and axis in (-1, logits.ndim - 1):
            # stays f32 through the reduction (bf16 accumulation over
            # thousands of tokens rounds the sum AND the mask-count
            # denominator); only the final result drops back
            per = _softmax_nll(logits, safe)
        elif axis in (-1, logits.ndim - 1):
            # prob-input path, same dense backward as the softmax one
            logp = jnp.log(jnp.maximum(logits, 1e-30))
            per = _pick_nll(logp, safe).astype(jnp.float32)
        else:
            if use_softmax:
                logp = jax.nn.log_softmax(logits, axis=axis)
            else:
                logp = jnp.log(jnp.maximum(logits, 1e-30))
            per = -jnp.take_along_axis(
                logp, safe[..., None], axis=axis)[..., 0]
            per = per.astype(jnp.float32)
        mask = (lab_i != ignore_index)
        per = jnp.where(mask, per, 0.0)
        out_dtype = logits.dtype
        if maybe_w:
            w = maybe_w[0][safe]
            per = per * jnp.where(mask, w, 0.0)
            if reduction == 'mean':
                denom = jnp.sum(
                    jnp.where(mask, w, 0.0).astype(jnp.float32))
                return (jnp.sum(per)
                        / jnp.maximum(denom, 1e-12)).astype(out_dtype)
        if reduction == 'mean':
            denom = jnp.maximum(jnp.sum(mask.astype(per.dtype)), 1.0)
            return (jnp.sum(per) / denom).astype(out_dtype)
        return _reduce(per, reduction).astype(out_dtype)

    return apply(fn, *ins, op_name='cross_entropy')


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction='none',
                         axis=axis)
    from .activation import softmax as _softmax
    # reference keeps the trailing 1-dim on hard labels
    if not soft_label:
        from ...tensor.manipulation import unsqueeze
        loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction='mean',
                         name=None):
    ins = [wrap(input), wrap(label)]
    if weight is not None:
        ins.append(wrap(weight))

    def fn(p, y, *maybe_w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        per = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if maybe_w:
            per = per * maybe_w[0]
        return _reduce(per, reduction)

    return apply(fn, *ins, op_name='binary_cross_entropy')


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction='mean', pos_weight=None,
                                     name=None):
    ins = [wrap(logit), wrap(label)]
    if weight is not None:
        ins.append(wrap(weight))
    if pos_weight is not None:
        ins.append(wrap(pos_weight))

    def fn(z, y, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]; i += 1
        if pos_weight is not None:
            pw = extra[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight
        if pw is not None:
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            per = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            per = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if w is not None:
            per = per * w
        return _reduce(per, reduction)

    return apply(fn, *ins, op_name='bce_with_logits')


def mse_loss(input, label, reduction='mean', name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction),
                 wrap(input), wrap(label), op_name='mse_loss')


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), wrap(input), wrap(label),
                 op_name='square_error_cost')


def l1_loss(input, label, reduction='mean', name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 wrap(input), wrap(label), op_name='l1_loss')


def nll_loss(input, label, weight=None, ignore_index=-100, reduction='mean',
             name=None):
    ins = [wrap(input), wrap(label)]
    if weight is not None:
        ins.append(wrap(weight))

    def fn(logp, lab, *maybe_w):
        if logp.ndim > 2:
            # reference contract: classes live at axis 1 for
            # (N, C, d1..dK) inputs with (N, d1..dK) labels
            logp = jnp.moveaxis(logp, 1, -1)
        lab_i = lab.astype(jnp.int32)
        safe = jnp.where(lab_i == ignore_index, 0, lab_i)
        per = _pick_nll(logp, safe)
        mask = lab_i != ignore_index
        per = jnp.where(mask, per, 0.0)
        if maybe_w:
            w = maybe_w[0][safe] * mask.astype(logp.dtype)
            if reduction == 'mean':
                return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1e-12)
            per = per * w
        if reduction == 'mean':
            return jnp.sum(per) / jnp.maximum(
                jnp.sum(mask.astype(logp.dtype)), 1.0)
        return _reduce(per, reduction)

    return apply(fn, *ins, op_name='nll_loss')


def kl_div(input, label, reduction='mean', name=None):
    def fn(logp, y):
        per = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == 'batchmean':
            return jnp.sum(per) / logp.shape[0]
        return _reduce(per, reduction)
    return apply(fn, wrap(input), wrap(label), op_name='kl_div')


def smooth_l1_loss(input, label, reduction='mean', delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        per = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce(per, reduction)
    return apply(fn, wrap(input), wrap(label), op_name='smooth_l1_loss')


def margin_ranking_loss(input, other, label, margin=0.0, reduction='mean',
                        name=None):
    def fn(a, b, y):
        per = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(per, reduction)
    return apply(fn, wrap(input), wrap(other), wrap(label),
                 op_name='margin_ranking_loss')


def hinge_embedding_loss(input, label, margin=1.0, reduction='mean',
                         name=None):
    def fn(a, y):
        per = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(per, reduction)
    return apply(fn, wrap(input), wrap(label),
                 op_name='hinge_embedding_loss')


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction='mean', name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        per = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(per, reduction)
    return apply(fn, wrap(input1), wrap(input2), wrap(label),
                 op_name='cosine_embedding_loss')


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction='sum', name=None):
    ins = [wrap(logit), wrap(label)]
    if normalizer is not None:
        ins.append(wrap(normalizer))

    def fn(z, y, *maybe_n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        per = a_t * ((1 - p_t) ** gamma) * ce
        if maybe_n:
            per = per / maybe_n[0]
        return _reduce(per, reduction)

    return apply(fn, *ins, op_name='sigmoid_focal_loss')


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, y):
        return -(y * jnp.log(p + epsilon) +
                 (1 - y) * jnp.log(1 - p + epsilon))
    return apply(fn, wrap(input), wrap(label), op_name='log_loss')


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction='mean'):
    """CTC via the standard forward algorithm in log space, lax.scan over
    time — compiler-friendly (no per-step Python), cf. the reference's
    warp-ctc kernel (paddle/fluid/operators/warpctc_op.cc)."""
    def fn(lp, lab, in_len, lab_len):
        # lp: [T, B, C] log-probs; lab: [B, S]
        T, B, C = lp.shape
        S = lab.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        L = 2 * S + 1
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        init = jnp.full((B, L), neg_inf)
        init = init.at[:, 0].set(lp[0, :, blank])
        init = init.at[:, 1].set(
            jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0])

        same = jnp.concatenate(
            [jnp.ones((B, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, xs):
            lp_t, t = xs
            a0 = alpha
            a1 = jnp.concatenate([jnp.full((B, 1), neg_inf),
                                  alpha[:, :-1]], axis=1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg_inf),
                                  alpha[:, :-2]], axis=1)
            a2 = jnp.where(same, neg_inf, a2)
            m = jnp.maximum(jnp.maximum(a0, a1), a2)
            s = (jnp.exp(a0 - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m))
            merged = m + jnp.log(jnp.maximum(s, 1e-37))
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            new = merged + emit
            # freeze rows whose sequence already ended (t >= input_length)
            active = (t < in_len.astype(jnp.int32))[:, None]
            return jnp.where(active, new, alpha), None

        alpha_T, _ = jax.lax.scan(
            step, init, (lp[1:], jnp.arange(1, T, dtype=jnp.int32)))
        # final: sum of positions L-1 and L-2 (adjusted by label length)
        idx_last = 2 * lab_len.astype(jnp.int32)
        idx_prev = idx_last - 1
        aL = jnp.take_along_axis(alpha_T, idx_last[:, None], axis=1)[:, 0]
        aP = jnp.take_along_axis(alpha_T, jnp.maximum(idx_prev, 0)[:, None],
                                 axis=1)[:, 0]
        m = jnp.maximum(aL, aP)
        ll = m + jnp.log(jnp.exp(aL - m) + jnp.exp(aP - m))
        per = -ll
        if reduction == 'mean':
            return jnp.mean(per / jnp.maximum(lab_len.astype(lp.dtype), 1.0))
        return _reduce(per, reduction)

    return apply(fn, wrap(log_probs), wrap(labels), wrap(input_lengths),
                 wrap(label_lengths), op_name='ctc_loss')


_hsigmoid_trees = {}


def _hsigmoid_default_tree(C):
    """Complete-binary-tree path tables (heap layout: root=1, leaf for
    class c at heap index C+c, internal node n -> weight row n-1),
    cached per num_classes — hierarchical sigmoid exists for huge C,
    so the O(C log C) host walk must run once, not per step."""
    import numpy as np_
    if C in _hsigmoid_trees:
        return _hsigmoid_trees[C]
    L = max(int(np_.ceil(np_.log2(max(C, 2)))), 1)
    tbl = np_.full((C, L), -1, np_.int64)
    code = np_.zeros((C, L), np_.float32)
    for c in range(C):
        node = C + c
        path = []
        while node > 1:
            parent = node // 2
            path.append((parent - 1, float(node % 2)))
            node = parent
        for k, (p, b) in enumerate(reversed(path)):
            if k < L:
                tbl[c, k] = p
                code[c, k] = b
    _hsigmoid_trees[C] = (tbl, code)
    return tbl, code


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: nn/functional/loss.py::
    hsigmoid_loss over the hsigmoid op).  Default tree: the complete
    binary tree over num_classes the reference builds — precomputed
    HOST-side as static [C, L] path-node/code tables, so the on-device
    work is two gathers + one BCE reduce (no per-class python).
    Custom trees come in via path_table/path_code [N, L] (or [C, L]),
    -1 padded."""
    import numpy as np_
    x, lb = wrap(input), wrap(label)
    w = wrap(weight)
    ins = [x, lb, w]
    if bias is not None:
        ins.append(wrap(bias))

    if path_table is None:
        path_table, path_code = _hsigmoid_default_tree(int(num_classes))
    pt = jnp.asarray(np_.asarray(path_table, np_.int64))
    pc = jnp.asarray(np_.asarray(path_code, np_.float32))

    def fn(v, y, wv, *b):
        y = y.reshape(v.shape[0]).astype(jnp.int32)
        nodes = pt[y]                       # [B, L]
        codes = pc[y]                       # [B, L]
        valid = (nodes >= 0).astype(v.dtype)
        safe = jnp.maximum(nodes, 0)
        wrow = wv[safe]                     # [B, L, D]
        logits = jnp.einsum('bd,bld->bl', v, wrow)
        if b:
            logits = logits + b[0].reshape(-1)[safe]
        # BCE with target = code bit
        ls = jax.nn.log_sigmoid(logits)
        per = -(codes * ls + (1 - codes) * (ls - logits))
        return (per * valid).sum(axis=-1, keepdims=True)

    return apply(fn, *ins, op_name='hsigmoid_loss')


__all__ += ['hsigmoid_loss']


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice loss for segmentation (reference
    fluid/layers/nn.py dice_loss): label [..., 1] int is one-hotted to
    input's class dim; per-sample dice over all non-batch dims."""
    input = wrap(input)
    label = wrap(label)
    n_cls = input.shape[-1]

    def fn(x, lab):
        if lab.shape and lab.shape[-1] == 1:
            lab = lab.squeeze(-1)
        oh = jax.nn.one_hot(lab.astype(jnp.int32), n_cls, dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inse = jnp.sum(x * oh, axis=red)
        denom = jnp.sum(x, axis=red) + jnp.sum(oh, axis=red)
        return jnp.mean(1.0 - 2.0 * inse / (denom + epsilon))
    return apply(fn, input, label, op_name='dice_loss')


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric-learning loss (reference fluid/layers/loss.py
    npair_loss): soft-label CE over the anchor@positive.T similarity
    matrix + Beta*l2_reg embedding regularizer."""
    anchor = wrap(anchor)
    positive = wrap(positive)
    labels = wrap(labels)

    def fn(a, p, lab):
        beta = 0.25
        b = lab.shape[0]
        eq = (lab.reshape(b, 1) == lab.reshape(1, b)).astype(a.dtype)
        soft = eq / jnp.sum(eq, axis=1, keepdims=True)
        l2 = (jnp.mean(jnp.sum(a * a, axis=1)) +
              jnp.mean(jnp.sum(p * p, axis=1))) * beta * l2_reg
        sim = a @ p.T
        ce_rows = -jnp.sum(soft * jax.nn.log_softmax(sim, axis=-1),
                           axis=-1, keepdims=True)
        ce = jnp.mean(jnp.sum(soft * ce_rows, axis=0))
        return l2 + ce
    return apply(fn, anchor, positive, labels, op_name='npair_loss')


__all__ += ['dice_loss', 'npair_loss']
