"""Weight initializers.

Reference analogue: /root/reference/python/paddle/nn/initializer/ and
fluid/initializer.py.  TPU-native: each initializer is a pure function of
(shape, dtype, PRNGKey); eager mode pulls keys from the global generator,
so paddle.seed() reproduces full init sequences.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core import rng
from ...core.dtype import convert_dtype, get_default_dtype

__all__ = [
    'Initializer', 'Constant', 'Normal', 'TruncatedNormal', 'Uniform',
    'XavierNormal', 'XavierUniform', 'KaimingNormal', 'KaimingUniform',
    'Assign', 'calculate_gain', 'set_global_initializer',
]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *spatial] (paddle layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    gains = {'sigmoid': 1.0, 'linear': 1.0, 'conv1d': 1.0, 'conv2d': 1.0,
             'conv3d': 1.0, 'tanh': 5.0 / 3.0, 'relu': math.sqrt(2.0),
             'leaky_relu': math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             'selu': 3.0 / 4.0}
    return gains[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype=None, key=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        if key is None:
            key = rng.next_key()
        return self._generate(tuple(shape), dtype, key)

    def _generate(self, shape, dtype, key):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype, key):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype, key):
        return self.mean + self.std * jax.random.normal(key, shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype, key):
        z = jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
        return self.mean + self.std * z


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _generate(self, shape, dtype, key):
        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity='relu'):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype, key):
        fi = self.fan_in if self.fan_in is not None else _fans(shape)[0]
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(key, shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity='relu'):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype, key):
        fi = self.fan_in if self.fan_in is not None else _fans(shape)[0]
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.assigned = value

    def _generate(self, shape, dtype, key):
        v = self.assigned
        v = v.value if hasattr(v, 'value') else jnp.asarray(np.asarray(v))
        return jnp.reshape(v.astype(dtype), shape)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init


def get_default_init(is_bias):
    if is_bias:
        return _global_bias_init or Constant(0.0)
    return _global_weight_init or XavierNormal()


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed conv (reference
    fluid/initializer.py::BilinearInitializer): weight[..., y, x] =
    (1-|x/f - c|)(1-|y/f - c|) with f = ceil(W/2), c = (2f-1-f%2)/(2f),
    so a ConvTranspose with stride f performs bilinear interpolation."""

    def _generate(self, shape, dtype, key):
        if len(shape) != 4:
            raise ValueError('Bilinear initializer expects a 4-D weight '
                             f'shape, got {shape}')
        H, W = shape[-2], shape[-1]
        f = int(np.ceil(W / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        x = jnp.arange(W, dtype=jnp.float32)
        y = jnp.arange(H, dtype=jnp.float32)
        vx = 1.0 - jnp.abs(x / f - c)
        vy = 1.0 - jnp.abs(y / f - c)
        k = vy[:, None] * vx[None, :]
        return jnp.broadcast_to(k, shape).astype(dtype)


__all__ += ['Bilinear']
