"""Gradient clipping.

Reference analogue: /root/reference/python/paddle/fluid/clip.py.
TPU-native: pytree-wide global-norm clip as pure jnp — inside a compiled
train step it fuses into the update; eager mode works on .grad tensors.
"""
import jax.numpy as jnp

__all__ = ['ClipGradByValue', 'ClipGradByNorm', 'ClipGradByGlobalNorm']


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list of (param, grad Tensor) — eager API."""
        return self._dygraph_clip(params_grads)

    def clip_values(self, grads):
        """grads: list/pytree of raw jnp arrays — functional API used by
        the compiled train step."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def clip_values(self, grads):
        return [jnp.clip(g, self.min, self.max) for g in grads]

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, 'need_clip', True) is False:
                out.append((p, g))
                continue
            ng = g.clone()
            ng.value = jnp.clip(g.value, self.min, self.max)
            out.append((p, ng))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def clip_values(self, grads):
        out = []
        for g in grads:
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append(g * scale)
        return out

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, 'need_clip', True) is False:
                out.append((p, g))
                continue
            ng = g.clone()
            ng.value = self.clip_values([g.value])[0]
            out.append((p, ng))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name='default_group'):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def clip_values(self, grads):
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in grads))
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        return [g * scale for g in grads]

    def _dygraph_clip(self, params_grads):
        gs = [g.value for p, g in params_grads
              if g is not None and getattr(p, 'need_clip', True)]
        if not gs:
            return params_grads
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in gs))
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, 'need_clip', True) is False:
                out.append((p, g))
                continue
            ng = g.clone()
            ng.value = g.value * scale
            out.append((p, ng))
        return out


class ErrorClipByValue:
    """Legacy error (gradient-of-output) clip attr (reference
    fluid/clip.py): kept for API parity — in the TPU-native stack it
    behaves like ClipGradByValue applied to the op's output grads,
    which the global clip path covers."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)


def set_gradient_clip(clip, param_list=None, program=None):
    """Legacy global clip setter (reference fluid/clip.py:
    set_gradient_clip writes the clip attr onto params).  The 2.x way
    — passing grad_clip= to the optimizer — is what our optimizers
    implement; this stores the clip per param for optimizers that
    consult it."""
    import warnings
    warnings.warn(
        'set_gradient_clip is the deprecated 1.x API: prefer '
        'passing grad_clip= to the optimizer (reference deprecated '
        'it the same way)', stacklevel=2)
    if param_list:
        for p in param_list:
            p.grad_clip = clip
    else:
        _GLOBAL_CLIP[0] = clip


_GLOBAL_CLIP = [None]


def get_gradient_clip():
    return _GLOBAL_CLIP[0]


__all__ += ['ErrorClipByValue', 'set_gradient_clip']
