"""Seq2seq decoding: Decoder protocol, BeamSearchDecoder, dynamic_decode.

Reference analogue: /root/reference/python/paddle/fluid/layers/rnn.py
(Decoder:753, BeamSearchDecoder:866, dynamic_decode:1581), re-exported as
paddle.nn.BeamSearchDecoder / paddle.nn.dynamic_decode.

TPU-native design: the per-step beam math (log_softmax, finished-beam
masking, top-k over beam*vocab, beam reordering) is pure jnp — one fused
XLA program per step; the backtrace (`finalize`) is a static-trip-count
`lax.scan` (see nn.functional.gather_tree).  The outer loop is host-side
like the reference's imperative path, with data-dependent stopping
(`all(finished)`); for a fully compiled decode, fix `max_step_num` and
wrap the step in jit.to_static — every step below is trace-safe.
"""
import collections

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..tensor._helpers import wrap, raw

__all__ = ['Decoder', 'BeamSearchDecoder', 'dynamic_decode']


class Decoder:
    """Base protocol for dynamic_decode: initialize/step/finalize."""

    def initialize(self, inits):
        """-> (initial_inputs, initial_states, finished)."""
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        """-> (outputs, next_states, next_inputs, finished)."""
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        """Optional post-processing of stacked outputs."""
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


def _tree_map(fn, tree):
    """map over a (possibly nested) structure of Tensors.  Tensors are
    opaque to jax pytrees, so they land as leaves."""
    return jax.tree_util.tree_map(
        fn, tree, is_leaf=lambda x: isinstance(x, Tensor))


class BeamSearchDecoder(Decoder):
    """Beam search over an RNNCell-like `cell`.

    cell(inputs, states) -> (outputs, next_states); `output_fn` maps cell
    outputs to vocab logits; `embedding_fn` maps token ids to the next
    step's inputs.  State/output structures mirror the reference's
    namedtuples so user code destructures identically.
    """

    OutputWrapper = collections.namedtuple(
        'OutputWrapper', ('scores', 'predicted_ids', 'parent_ids'))
    StateWrapper = collections.namedtuple(
        'StateWrapper', ('cell_states', 'log_probs', 'finished', 'lengths'))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self._batch = None

    # -- beam/batch layout helpers -------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] by repeating each batch entry
        (reference BeamSearchDecoder.tile_beam_merge_with_batch).
        Leading dims are computed explicitly — a -1 reshape cannot be
        inferred on zero-size state leaves (e.g. an empty prefix)."""
        v = raw(wrap(x))
        v = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(v.reshape((v.shape[0] * beam_size,) + v.shape[2:]))

    def _split(self, v):
        return v.reshape((self._batch, self.beam_size) + v.shape[1:])

    def _merge(self, v):
        return v.reshape((v.shape[0] * v.shape[1],) + v.shape[2:])

    # -- Decoder protocol ----------------------------------------------
    def initialize(self, initial_cell_states):
        leaves = [t for t in jax.tree_util.tree_leaves(
            initial_cell_states,
            is_leaf=lambda x: isinstance(x, Tensor))]
        self._batch = wrap(leaves[0]).shape[0]
        K = self.beam_size
        cell_states = _tree_map(
            lambda t: self.tile_beam_merge_with_batch(t, K),
            initial_cell_states)
        start = jnp.full((self._batch * K,), self.start_token, jnp.int32)
        inputs = self.embedding_fn(Tensor(start)) if self.embedding_fn \
            else Tensor(start)
        # beam 0 active, others -inf: the first step expands one beam
        lp = jnp.tile(
            jnp.array([0.0] + [-np.inf] * (K - 1), jnp.float32)[None, :],
            (self._batch, 1))
        finished = jnp.zeros((self._batch, K), bool)
        lengths = jnp.zeros((self._batch, K), jnp.int32)
        state = self.StateWrapper(cell_states, Tensor(lp),
                                  Tensor(finished), Tensor(lengths))
        return inputs, state, Tensor(finished)

    def step(self, time, inputs, states, **kwargs):
        cell_out, next_cell_states = self.cell(inputs, states.cell_states,
                                               **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        B, K = self._batch, self.beam_size
        logits = raw(wrap(cell_out)).astype(jnp.float32)
        V = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, V)
        finished = raw(states.finished)
        # finished beams may only emit end_token, at zero added logprob
        only_end = jnp.full((V,), -np.inf, jnp.float32) \
            .at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], only_end, step_lp)
        total = raw(states.log_probs)[..., None] + step_lp
        scores, top_idx = jax.lax.top_k(total.reshape(B, K * V), K)
        parent = (top_idx // V).astype(jnp.int32)
        token = (top_idx % V).astype(jnp.int32)

        prev_fin = jnp.take_along_axis(finished, parent, axis=1)
        next_fin = prev_fin | (token == self.end_token)
        lengths = jnp.take_along_axis(raw(states.lengths), parent, axis=1) \
            + (~prev_fin).astype(jnp.int32)

        def reorder(t):
            v = self._split(raw(wrap(t)))
            idx = parent.reshape(parent.shape + (1,) * (v.ndim - 2))
            return Tensor(self._merge(
                jnp.take_along_axis(v, idx, axis=1)))
        next_cell_states = _tree_map(reorder, next_cell_states)

        outputs = self.OutputWrapper(Tensor(scores), Tensor(token),
                                     Tensor(parent))
        next_state = self.StateWrapper(next_cell_states, Tensor(scores),
                                       Tensor(next_fin), Tensor(lengths))
        flat_tok = token.reshape(B * K)
        next_inputs = self.embedding_fn(Tensor(flat_tok)) \
            if self.embedding_fn else Tensor(flat_tok)
        return outputs, next_state, next_inputs, Tensor(next_fin)

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrace the beam tree -> predicted_ids [T, B, beam]."""
        from .functional import gather_tree
        predicted_ids = gather_tree(outputs.predicted_ids,
                                    outputs.parent_ids)
        return predicted_ids, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run `decoder` until every sequence finishes or max_step_num steps
    (reference fluid/layers/rnn.py:1581).  Returns (outputs, final_states
    [, sequence_lengths]) with outputs batch-major unless
    output_time_major."""
    inputs, states, finished = decoder.initialize(inits)
    fin = raw(finished)
    seq_lengths = jnp.zeros_like(fin, jnp.int32)
    collected = None
    step = 0
    while not bool(jnp.all(fin)):
        t = Tensor(jnp.asarray([step], jnp.int32))
        outputs, next_states, next_inputs, next_finished = \
            decoder.step(t, inputs, states, **kwargs)
        if not decoder.tracks_own_finished:
            nf = raw(next_finished) | fin
            seq_lengths = seq_lengths + (~fin).astype(jnp.int32)
            if impute_finished:  # hold finished entries' states constant
                next_states = jax.tree_util.tree_map(
                    lambda old, new: Tensor(jnp.where(
                        _bmask(fin, raw(wrap(new))), raw(wrap(old)),
                        raw(wrap(new)))),
                    states, next_states,
                    is_leaf=lambda x: isinstance(x, Tensor))
            next_finished = Tensor(nf)
        else:
            seq_lengths = raw(getattr(next_states, 'lengths', Tensor(
                seq_lengths)))
        collected = jax.tree_util.tree_map(
            lambda x: [x], outputs,
            is_leaf=lambda x: isinstance(x, Tensor)) if collected is None \
            else jax.tree_util.tree_map(
                lambda x, acc: acc + [x], outputs, collected,
                is_leaf=lambda x: isinstance(x, Tensor))
        inputs, states = next_inputs, next_states
        fin = raw(next_finished)
        step += 1
        if max_step_num is not None and step > max_step_num:
            break

    stacked = jax.tree_util.tree_map(
        lambda acc: Tensor(jnp.stack([raw(t) for t in acc], axis=0)),
        collected,
        is_leaf=lambda x: isinstance(x, list) and
        all(isinstance(t, Tensor) for t in x))
    final_states = states
    try:
        stacked, final_states = decoder.finalize(stacked, final_states,
                                                 Tensor(seq_lengths))
    except NotImplementedError:
        pass
    if not output_time_major:
        stacked = jax.tree_util.tree_map(
            lambda x: Tensor(jnp.moveaxis(raw(x), 0, 1)), stacked,
            is_leaf=lambda x: isinstance(x, Tensor))
    if return_length:
        return stacked, final_states, Tensor(seq_lengths)
    return stacked, final_states


def _bmask(fin, new):
    """Broadcast the [B(,K)] finished mask against a state leaf."""
    return fin.reshape(fin.shape + (1,) * (new.ndim - fin.ndim))
