"""nn.utils — weight/spectral norm reparameterizations.

Reference analogue: /root/reference/python/paddle/nn/utils/.
Implemented as forward pre-hooks that recompute the wrapped parameter,
mirroring the reference's hook-based approach.
"""
import jax.numpy as jnp

from ...core.tensor import Parameter

__all__ = ['weight_norm', 'remove_weight_norm', 'spectral_norm']


def _norm_except(v, axis):
    if axis is None:
        return jnp.sqrt(jnp.sum(jnp.square(v)))
    axes = tuple(i for i in range(v.ndim) if i != axis)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def weight_norm(layer, name='weight', dim=0):
    w = getattr(layer, name)
    g = Parameter(_norm_except(w.value, dim))
    v = Parameter(w.value)
    layer.add_parameter(name + '_g', g)
    layer.add_parameter(name + '_v', v)
    del layer._parameters[name]

    def hook(l, inputs):
        vv = getattr(l, name + '_v')
        gg = getattr(l, name + '_g')
        from ...core.dispatch import apply
        w_new = apply(
            lambda vvv, ggg: vvv * (ggg / _norm_except(vvv, dim)), vv, gg,
            op_name='weight_norm')
        object.__setattr__(l, '_wn_cache_' + name, w_new)
        l._parameters.pop(name, None)
        l.__dict__[name] = w_new

    handle = layer.register_forward_pre_hook(hook)
    layer._wn_handle = handle
    hook(layer, None)
    return layer


def remove_weight_norm(layer, name='weight'):
    if hasattr(layer, '_wn_handle'):
        layer._wn_handle.remove()
    w = layer.__dict__.pop(name, None)
    if w is not None:
        layer.add_parameter(name, Parameter(w.value))
    for suffix in ('_g', '_v'):
        layer._parameters.pop(name + suffix, None)
    return layer


def spectral_norm(layer, name='weight', n_power_iterations=1, eps=1e-12,
                  dim=None):
    import jax
    from ...core import rng
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    wm = jnp.moveaxis(w.value, dim, 0).reshape(w.value.shape[dim], -1)
    u0 = jax.random.normal(rng.next_key(), (wm.shape[0],))
    from ...core.tensor import Tensor
    layer.register_buffer(name + '_u', Tensor(u0 / jnp.linalg.norm(u0)))

    def hook(l, inputs):
        wp = l._parameters.get(name) or getattr(l, name + '_orig')
        u = getattr(l, name + '_u').value
        wmat = jnp.moveaxis(wp.value, dim, 0).reshape(wp.value.shape[dim],
                                                      -1)
        for _ in range(n_power_iterations):
            v = wmat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = wmat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ wmat @ v
        getattr(l, name + '_u').value = u
        from ...core.dispatch import apply
        w_new = apply(lambda ww: ww / sigma, wp, op_name='spectral_norm')
        if name in l._parameters:
            l.add_parameter(name + '_orig', l._parameters.pop(name))
        l.__dict__[name] = w_new

    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer
