"""Recurrent layers.

Reference analogue: /root/reference/python/paddle/nn/layer/rnn.py (cuDNN
RNN kernels + per-step dygraph loop).  TPU-native: the WHOLE sequence is
one lax.scan — a single XLA while-loop with fused cell math, no per-step
Python dispatch, fully differentiable (scan has a native VJP).
"""
import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...tensor._helpers import wrap
from ..initializer import Uniform
from .layers import Layer

__all__ = ['SimpleRNNCell', 'LSTMCell', 'GRUCell', 'RNN', 'BiRNN',
           'SimpleRNN', 'LSTM', 'GRU', 'RNNCellBase']


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0):
        from ...tensor.creation import full
        batch = batch_ref.shape[0]
        return full([batch, self.hidden_size], init_value,
                    dtype or 'float32')


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation='tanh',
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    def _step(self, x, h, w_ih, w_hh, b_ih, b_hh):
        act = jnp.tanh if self.activation == 'tanh' else jax.nn.relu
        return act(x @ w_ih.T + b_ih + h @ w_hh.T + b_hh)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = apply(self._step, wrap(inputs), wrap(states), self.weight_ih,
                    self.weight_hh, self.bias_ih, self.bias_hh,
                    op_name='simple_rnn_cell')
        return out, out

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    @staticmethod
    def _step(x, h, c, w_ih, w_hh, b_ih, b_hh):
        gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return h2, c2

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states
        h2, c2 = apply(self._step, wrap(inputs), wrap(h), wrap(c),
                       self.weight_ih, self.weight_hh, self.bias_ih,
                       self.bias_hh, op_name='lstm_cell')
        return h2, (h2, c2)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    @staticmethod
    def _step(x, h, w_ih, w_hh, b_ih, b_hh):
        gi = x @ w_ih.T + b_ih
        gh = h @ w_hh.T + b_hh
        ir, iz, ig = jnp.split(gi, 3, axis=-1)
        hr, hz, hg = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        g = jnp.tanh(ig + r * hg)
        return (1 - z) * g + z * h

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h2 = apply(self._step, wrap(inputs), wrap(states), self.weight_ih,
                   self.weight_hh, self.bias_ih, self.bias_hh,
                   op_name='gru_cell')
        return h2, h2

    @property
    def state_shape(self):
        return (self.hidden_size,)


def _scan_layer(cell_kind, x, h0, c0, w_ih, w_hh, b_ih, b_hh, reverse,
                time_major):
    """One direction of one recurrent layer as a single lax.scan."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)  # [T, B, F]
    if reverse:
        x = jnp.flip(x, 0)

    if cell_kind == 'LSTM':
        def step(carry, xt):
            h, c = carry
            h2, c2 = LSTMCell._step(xt, h, c, w_ih, w_hh, b_ih, b_hh)
            return (h2, c2), h2
        (hT, cT), ys = jax.lax.scan(step, (h0, c0), x)
    elif cell_kind == 'GRU':
        def step(h, xt):
            h2 = GRUCell._step(xt, h, w_ih, w_hh, b_ih, b_hh)
            return h2, h2
        hT, ys = jax.lax.scan(step, h0, x)
        cT = hT
    else:
        act = jnp.tanh if cell_kind == 'RNN_TANH' else jax.nn.relu
        def step(h, xt):
            h2 = act(xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh)
            return h2, h2
        hT, ys = jax.lax.scan(step, h0, x)
        cT = hT
    if reverse:
        ys = jnp.flip(ys, 0)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, hT, cT


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrent stack."""

    CELL_KIND = 'RNN_TANH'
    GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction='forward', time_major=False, dropout=0.0,
                 activation='tanh', weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ('bidirect', 'bidirectional')
        self.num_directions = 2 if self.bidirectional else 1
        if activation == 'relu':
            self.CELL_KIND = 'RNN_RELU'
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        g = self.GATES
        self._weights = []
        for layer in range(num_layers):
            for direction in range(self.num_directions):
                in_sz = input_size if layer == 0 else \
                    hidden_size * self.num_directions
                suffix = '_reverse' if direction else ''
                w_ih = self.create_parameter(
                    [g * hidden_size, in_sz], attr=weight_ih_attr,
                    default_initializer=init)
                w_hh = self.create_parameter(
                    [g * hidden_size, hidden_size], attr=weight_hh_attr,
                    default_initializer=init)
                b_ih = self.create_parameter(
                    [g * hidden_size], attr=bias_ih_attr, is_bias=True,
                    default_initializer=init)
                b_hh = self.create_parameter(
                    [g * hidden_size], attr=bias_hh_attr, is_bias=True,
                    default_initializer=init)
                self.add_parameter(f'weight_ih_l{layer}{suffix}', w_ih)
                self.add_parameter(f'weight_hh_l{layer}{suffix}', w_hh)
                self.add_parameter(f'bias_ih_l{layer}{suffix}', b_ih)
                self.add_parameter(f'bias_hh_l{layer}{suffix}', b_hh)
                self._weights.append((w_ih, w_hh, b_ih, b_hh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.creation import zeros
        x = wrap(inputs)
        batch_axis = 1 if self.time_major else 0
        batch = x.shape[batch_axis]
        L, D = self.num_layers, self.num_directions
        is_lstm = self.CELL_KIND == 'LSTM'
        if initial_states is None:
            h0 = zeros([L * D, batch, self.hidden_size])
            c0 = zeros([L * D, batch, self.hidden_size])
        elif is_lstm:
            h0, c0 = initial_states
        else:
            h0, c0 = initial_states, None

        flat_w = [w for tup in self._weights for w in tup]
        kind = self.CELL_KIND
        time_major = self.time_major
        bidi = self.bidirectional
        hidden = self.hidden_size
        dropout = self.dropout
        training = self.training

        def fn(xv, h0v, c0v, *weights):
            from ...core import rng
            out = xv
            h_finals, c_finals = [], []
            for layer in range(L):
                outs_dir = []
                for d in range(D):
                    idx = layer * D + d
                    w_ih, w_hh, b_ih, b_hh = weights[4 * idx:4 * idx + 4]
                    ys, hT, cT = _scan_layer(
                        kind, out, h0v[idx],
                        c0v[idx] if c0v is not None else h0v[idx],
                        w_ih, w_hh, b_ih, b_hh, reverse=bool(d),
                        time_major=time_major)
                    outs_dir.append(ys)
                    h_finals.append(hT)
                    c_finals.append(cT)
                out = jnp.concatenate(outs_dir, axis=-1) if bidi else \
                    outs_dir[0]
                if dropout > 0 and training and layer < L - 1:
                    keep = jax.random.bernoulli(
                        rng.next_key(), 1 - dropout, out.shape)
                    out = jnp.where(keep, out / (1 - dropout), 0.0)
            hN = jnp.stack(h_finals, 0)
            cN = jnp.stack(c_finals, 0)
            return out, hN, cN

        args = [x, wrap(h0)]
        if is_lstm:
            fn_c = fn
            args.append(wrap(c0))
        else:
            def fn_c(xv, h0v, *weights):
                return fn(xv, h0v, None, *weights)
        out, hN, cN = apply(fn_c, *args, *flat_w, op_name='rnn')
        if is_lstm:
            return out, (hN, cN)
        return out, hN


class SimpleRNN(_RNNBase):
    CELL_KIND = 'RNN_TANH'
    GATES = 1


class LSTM(_RNNBase):
    CELL_KIND = 'LSTM'
    GATES = 4


class GRU(_RNNBase):
    CELL_KIND = 'GRU'
    GATES = 3


class RNN(Layer):
    """Generic sequence wrapper around a cell (reference rnn.py:RNN).
    Runs the cell per-step via lax.scan using the cell's _step math when
    available, else a python loop over time (still traced once under jit).
    """

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import stack, unstack, flip
        x = wrap(inputs)
        seq = unstack(x, axis=0 if self.time_major else 1)
        if self.is_reverse:
            seq = seq[::-1]
        states = initial_states
        outs = []
        for xt in seq:
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        y = stack(outs, axis=0 if self.time_major else 1)
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        y_fw, s_fw = self.rnn_fw(inputs, st_fw)
        y_bw, s_bw = self.rnn_bw(inputs, st_bw)
        return concat([y_fw, y_bw], axis=-1), (s_fw, s_bw)


# reference nn/layer/rnn.py module helpers: flatten/unflatten the
# [num_layers * num_directions, B, H] stacked state layout
def split_states(states, bidirectional=False, state_components=1):
    from ...tensor.manipulation import unbind
    if state_components == 1:
        st = list(unbind(states, axis=0))
        if not bidirectional:
            return st
        return [(st[2 * i], st[2 * i + 1]) for i in range(len(st) // 2)]
    comp = [list(unbind(s, axis=0)) for s in states]
    rows = list(zip(*comp))
    if not bidirectional:
        return [tuple(r) for r in rows]
    return [(tuple(rows[2 * i]), tuple(rows[2 * i + 1]))
            for i in range(len(rows) // 2)]


def concat_states(states, bidirectional=False, state_components=1):
    from ...tensor.manipulation import stack
    flat = []

    def walk(s):
        if isinstance(s, (list, tuple)):
            for t in s:
                walk(t)
        else:
            flat.append(s)

    walk(states)
    if state_components == 1:
        return stack(flat, axis=0)
    comps = [flat[k::state_components] for k in range(state_components)]
    return tuple(stack(c, axis=0) for c in comps)


RNNBase = _RNNBase  # reference-name alias

__all__ += ['split_states', 'concat_states', 'RNNBase']
