"""nn.Layer — the module system.

Reference analogue: /root/reference/python/paddle/fluid/dygraph/layers.py
(class Layer).  TPU-native addition: `functional_state()` /
`load_functional_state()` expose the whole parameter/buffer tree as a
JAX pytree so paddle_tpu.jit can close a Layer into a pure function for
XLA compilation — the reference has no such path because its executor
walks a C++ graph instead.
"""
import collections

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, Parameter
from ...core.dtype import convert_dtype, get_default_dtype
from ..initializer import get_default_init


class ParamAttr:
    """Reference analogue: python/paddle/fluid/param_attr.py."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None or isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        # bare initializer
        return ParamAttr(initializer=attr)


class Layer:
    # unique_name.generate analogue: per-prefix counters numbered from
    # zero, matching the reference's 'fc_0, fc_1' convention
    _name_counters = {}

    def __init__(self, name_scope=None, dtype='float32'):
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True
        self._name_scope = name_scope or self.__class__.__name__.lower()
        n = Layer._name_counters.get(self._name_scope, 0)
        Layer._name_counters[self._name_scope] = n + 1
        self._full_name = f'{self._name_scope}_{n}'

    # -- attribute magic -----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get('_parameters')
        layers = self.__dict__.get('_sub_layers')
        buffers = self.__dict__.get('_buffers')
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError(
                    "call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError(
                    "call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None:
                params.pop(name, None)
            if layers is not None:
                layers.pop(name, None)
            if buffers is not None and isinstance(value, Tensor):
                if name in buffers:
                    buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ('_parameters', '_sub_layers', '_buffers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ('_parameters', '_sub_layers', '_buffers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def full_name(self):
        """Unique name: name_scope + '_' + counter (reference
        layers.py:239, unique_name.generate analogue)."""
        return self._full_name

    def create_variable(self, name=None, persistable=None, dtype=None):
        """An uninitialized (empty) tensor owned by this layer
        (reference layers.py:418)."""
        dt = convert_dtype(dtype) or self._dtype
        t = Tensor(jnp.zeros((0,), dt), stop_gradient=True, name=name)
        t.persistable = bool(persistable)
        return t

    # reference layers.py:467 — create_tensor is the 2.x alias
    create_tensor = create_variable

    def backward(self, *inputs):
        raise ValueError("Layer shouldn't implement backward")

    # -- parameter management ------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        else:
            init = get_default_init(is_bias)
        value = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(value, name=attr.name if attr else None,
                      trainable=attr.trainable if attr else True)
        if attr is not None:
            p.optimize_attr = {'learning_rate': attr.learning_rate}
            p.regularizer = attr.regularizer
            p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- traversal -----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def clear_gradients(self):
        """Clear every parameter's .grad (reference
        fluid/dygraph/layers.py::Layer.clear_gradients — the 1.x
        counterpart of optimizer.clear_grad)."""
        for p in self.parameters():
            p.clear_grad()

    def named_parameters(self, prefix='', include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix='', include_sublayers=True):
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for bname, b in layer._buffers.items():
                if b is None:
                    continue
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def children(self):
        return list(self._sub_layers.values())

    def named_children(self):
        return list(self._sub_layers.items())

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix='', include_self=False):
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix,
                                           include_self=True)

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -- train / eval --------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   use_hook=True):
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[name] = p
        for name, b in self.named_buffers():
            leaf = name.rsplit('.', 1)[-1]
            owner = self._locate_owner(name)
            if leaf in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        # torch-style extra state: layers owning non-tensor state (e.g.
        # a host-resident embedding table) expose it via
        # get_extra_state/set_extra_state and it travels under
        # '<prefix>._extra_state' in every parent's state_dict
        for prefix, layer in self.named_sublayers(include_self=True):
            if hasattr(layer, 'get_extra_state'):
                key = (prefix + '.' if prefix else '') + '_extra_state'
                dest[key] = layer.get_extra_state()
        return dest

    def _locate_owner(self, qualname):
        obj = self
        parts = qualname.split('.')[:-1]
        for p in parts:
            obj = obj._sub_layers.get(p, obj) if isinstance(obj, Layer) \
                else obj
        return obj if isinstance(obj, Layer) else self

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            src = state_dict[name]
            if name.rsplit('.', 1)[-1] == '_extra_state':
                owner = self._locate_owner(name)
                if hasattr(owner, 'set_extra_state'):
                    owner.set_extra_state(src)
                continue
            v = src.value if isinstance(src, Tensor) else jnp.asarray(
                np.asarray(src))
            if tuple(v.shape) != tuple(target.value.shape):
                raise ValueError(
                    f"shape mismatch for {name}: {v.shape} vs "
                    f"{target.value.shape}")
            target.set_value(v)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- functional bridge (TPU compile path) --------------------------------
    def functional_state(self):
        """(params, buffers) as name→jnp.ndarray dicts — a JAX pytree."""
        params = {n: p.value for n, p in self.named_parameters()}
        buffers = {n: b.value for n, b in self.named_buffers()}
        return params, buffers

    def load_functional_state(self, params=None, buffers=None):
        """Write raw arrays back into the live Parameters/buffers."""
        if params:
            live = dict(self.named_parameters())
            for n, v in params.items():
                live[n].value = v
        if buffers:
            live = dict(self.named_buffers())
            for n, v in buffers.items():
                live[n].value = v

    # -- dtype migration (AMP O2) -------------------------------------------
    def to(self, dtype=None):
        if dtype is not None:
            d = convert_dtype(dtype)
            for p in self.parameters():
                p.value = p.value.astype(d)
            for b in self.buffers():
                if jnp.issubdtype(b.value.dtype, jnp.floating):
                    b.value = b.value.astype(d)
            for layer in self.sublayers(include_self=True):
                layer._dtype = d
        return self

    float = lambda self: self.to('float32')  # noqa: E731

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def extra_repr(self):
        return ''

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split('\n')
            sub_repr = '\n  '.join(sub_repr)
            lines.append(f"({name}): {sub_repr}")
        body = ''
        if lines:
            body = '\n  ' + '\n  '.join(lines) + '\n'
        return f"{type(self).__name__}({extra}{body})"


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks_dict):
        self._hooks = hooks_dict
        self.id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self.id, None)
