"""Vision layers (reference: python/paddle/nn/layer/vision.py)."""
from .. import functional as F
from .layers import Layer

__all__ = ['PixelShuffle']


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format='NCHW', name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)
