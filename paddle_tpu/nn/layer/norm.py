"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = ['BatchNorm', 'BatchNorm1D', 'BatchNorm2D', 'BatchNorm3D',
           'SyncBatchNorm', 'LayerNorm', 'GroupNorm', 'InstanceNorm1D',
           'InstanceNorm2D', 'InstanceNorm3D', 'LocalResponseNorm']


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer('_mean',
                             Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer('_variance',
                             Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum,
            epsilon=self.epsilon, data_format=self.data_format,
            use_global_stats=self.use_global_stats)


class BatchNorm(_BatchNormBase):
    """1D/2D/3D-agnostic alias (reference fluid.dygraph.BatchNorm)."""


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NCL',
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NCDHW',
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, cross-replica stats come from psum inside the compiled
    step (see distributed/fleet); eager single-chip falls back to local
    stats, matching the reference's single-card behavior."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        # walk and convert BatchNorm* sublayers in place
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(
                    sub, SyncBatchNorm):
                new = SyncBatchNorm(sub.num_features, sub.momentum,
                                    sub.epsilon,
                                    data_format=sub.data_format)
                new.weight.set_value(sub.weight.value)
                new.bias.set_value(sub.bias.value)
                new._mean.set_value(sub._mean.value)
                new._variance.set_value(sub._variance.value)
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            self.normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight,
                            self.bias, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon,
                               data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format='NCL',
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format='NCDHW',
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format='NCHW', name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Standalone spectral-normalization layer (reference:
    nn/layer/norm.py::SpectralNorm): forward(weight) returns
    weight / sigma_max, sigma estimated by power iteration.  The u/v
    vectors re-derive from a fixed key per call — stateless and
    traceable (see static/nn.py::spectral_norm for the rationale)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps

    def forward(self, weight):
        from ...static.nn import spectral_norm as _sn
        return _sn(weight, dim=self._dim, power_iters=self._power_iters,
                   eps=self._eps)


__all__ += ['SpectralNorm']
