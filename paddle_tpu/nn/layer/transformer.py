"""Transformer layers.

Reference analogue: /root/reference/python/paddle/nn/layer/transformer.py.
TPU-native: attention is bf16-friendly einsum onto the MXU; on TPU the
fused Pallas flash-attention kernel (paddle_tpu.ops.flash_attention) is
used for long sequences via nn.functional.scaled_dot_product_attention.

Incremental decoding (reference transformer.py:151 Cache/StaticCache,
:270 gen_cache): `Cache` holds projected k/v of ALL previous positions
[B, H, L_past, Dh] and each cached forward concatenates the new step's
k/v — attention work per emitted token is O(L), not O(L^2).
`StaticCache` holds the k/v computed ONCE over the encoder memory for
cross attention.  This eager concat path mirrors the reference's; a
jit-compiled decode loop instead wants static shapes — models/gpt.py
shows the preallocated-buffer + `lax.dynamic_update_slice` variant.
"""
import collections
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...tensor._helpers import wrap
from .. import functional as F
from .layers import Layer
from .common import Linear, Dropout
from .norm import LayerNorm
from .container import LayerList

__all__ = ['MultiHeadAttention', 'TransformerEncoderLayer',
           'TransformerEncoder', 'TransformerDecoderLayer',
           'TransformerDecoder', 'Transformer']


def _convert_attn_mask(mask, dtype):
    if mask is None:
        return None
    m = mask.value if hasattr(mask, 'value') else jnp.asarray(mask)
    if m.dtype == jnp.bool_:
        return jnp.where(m, 0.0, -1e9).astype(dtype)
    return m.astype(dtype)


class MultiHeadAttention(Layer):

    #: projected k/v of previous positions for decoder SELF attention
    #: in incremental decoding — grows by one step per cached forward
    #: (reference transformer.py:151)
    Cache = collections.namedtuple('Cache', ['k', 'v'])
    #: k/v computed once over encoder memory for CROSS attention —
    #: constant across decoding steps
    StaticCache = collections.namedtuple('StaticCache', ['k', 'v'])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, t):
        """[B, L, E] -> [B, H, L, Dh]."""
        H, Dh = self.num_heads, self.head_dim

        def fn(v):
            B, L, _ = v.shape
            return v.reshape(B, L, H, Dh).transpose(0, 2, 1, 3)
        return apply(fn, t, op_name='split_heads')

    def compute_kv(self, key, value):
        """Project + split-heads keys/values -> ([B,H,L,Dh], [B,H,L,Dh]).
        Exposed so callers can pre-compute a StaticCache over encoder
        memory (reference transformer.py:239 compute_kv)."""
        return (self._split_heads(self.k_proj(key)),
                self._split_heads(self.v_proj(value)))

    def gen_cache(self, key, value=None, type=None):
        """Build a Cache/StaticCache for forward (reference
        transformer.py:270).  `type=StaticCache`: k/v computed from
        (key, value) now and reused every step.  `type=Cache`,
        value=None: empty [B, H, 0, Dh] buffers to start incremental
        decoding.  `type=Cache` with value: seed the incremental cache
        with given k/v (UniLM-style prefix)."""
        if type is None:
            type = MultiHeadAttention.Cache
        if type == MultiHeadAttention.StaticCache:
            k, v = self.compute_kv(key, value)
            return self.StaticCache(k, v)
        if value is None:
            from ...core.tensor import Tensor
            kq = wrap(key)
            B = kq.shape[0]
            dt = kq.value.dtype if hasattr(kq, 'value') else jnp.float32
            empty = jnp.zeros((B, self.num_heads, 0, self.head_dim), dt)
            return self.Cache(Tensor(empty), Tensor(empty))
        return self.Cache(key, value)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = key if value is None else value
        H, Dh = self.num_heads, self.head_dim
        dropout = self.dropout if self.training else 0.0
        need_weights = self.need_weights

        if cache is None:
            # training/encoder fast path: one fused op, no head-split
            # round trips
            q = self.q_proj(query)
            k = self.k_proj(key)
            v = self.v_proj(value)

            def attn(qv, kv, vv):
                from ...core import rng
                B, Lq, _ = qv.shape
                Lk = kv.shape[1]
                qh = qv.reshape(B, Lq, H, Dh).transpose(0, 2, 1, 3)
                kh = kv.reshape(B, Lk, H, Dh).transpose(0, 2, 1, 3)
                vh = vv.reshape(B, Lk, H, Dh).transpose(0, 2, 1, 3)
                scores = jnp.einsum('bhqd,bhkd->bhqk', qh, kh) \
                    / math.sqrt(Dh)
                m = _convert_attn_mask(attn_mask, scores.dtype)
                if m is not None:
                    scores = scores + m
                weights = jax.nn.softmax(scores, axis=-1)
                p = weights
                if dropout > 0:
                    keep = jax.random.bernoulli(rng.next_key(),
                                                1 - dropout, p.shape)
                    p = jnp.where(keep, p / (1 - dropout), 0.0)
                out = jnp.einsum('bhqk,bhkd->bhqd', p, vh)
                out = out.transpose(0, 2, 1, 3).reshape(B, Lq, H * Dh)
                if need_weights:
                    return out, weights
                return out

            if need_weights:
                ctx, weights = apply(attn, q, k, v,
                                     op_name='multihead_attention')
                return self.out_proj(ctx), weights
            ctx = apply(attn, q, k, v, op_name='multihead_attention')
            return self.out_proj(ctx)

        # cached (incremental decode) path
        qh = self._split_heads(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            kh, vh = cache.k, cache.v
        else:
            kh, vh = self.compute_kv(key, value)
        if isinstance(cache, self.Cache):
            # append this step's k/v behind all previous positions
            kh = apply(lambda a, b: jnp.concatenate([a, b], axis=2),
                       cache.k, kh, op_name='cache_concat')
            vh = apply(lambda a, b: jnp.concatenate([a, b], axis=2),
                       cache.v, vh, op_name='cache_concat')
            cache = self.Cache(kh, vh)

        def attn_h(qv, kv, vv):
            from ...core import rng
            B, _, Lq, _ = qv.shape
            scores = jnp.einsum('bhqd,bhkd->bhqk', qv, kv) / math.sqrt(Dh)
            m = _convert_attn_mask(attn_mask, scores.dtype)
            if m is not None:
                scores = scores + m
            weights = jax.nn.softmax(scores, axis=-1)
            p = weights
            if dropout > 0:
                keep = jax.random.bernoulli(rng.next_key(), 1 - dropout,
                                            p.shape)
                p = jnp.where(keep, p / (1 - dropout), 0.0)
            out = jnp.einsum('bhqk,bhkd->bhqd', p, vv)
            out = out.transpose(0, 2, 1, 3).reshape(B, Lq, H * Dh)
            if need_weights:
                return out, weights
            return out

        if need_weights:
            ctx, weights = apply(attn_h, qh, kh, vh,
                                 op_name='multihead_attention_cached')
            return self.out_proj(ctx), weights, cache
        ctx = apply(attn_h, qh, kh, vh,
                    op_name='multihead_attention_cached')
        return self.out_proj(ctx), cache


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation='relu', attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            # UniLM-style incremental encoding (reference
            # transformer.py:566)
            src, incremental_cache = self.self_attn(src, src, src,
                                                    src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(
            self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        """-> MultiHeadAttention.Cache with empty [B, H, 0, Dh] buffers
        (reference transformer.py:585)."""
        return self.self_attn.gen_cache(src, type=self.self_attn.Cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [encoder_layer] +
            [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        out = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, src_mask)
            else:
                out, new_cache = layer(out, src_mask, cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)

    def gen_cache(self, src):
        """Per-layer incremental caches (reference transformer.py:695)."""
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation='relu', attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt,
                                                    tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory,
                                                memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(
            self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache,
                                                static_cache))

    def gen_cache(self, memory):
        """-> (incremental_cache, static_cache): empty self-attn Cache +
        cross-attn StaticCache over `memory` (reference
        transformer.py:916)."""
        incremental_cache = self.self_attn.gen_cache(
            memory, type=self.self_attn.Cache)
        static_cache = self.cross_attn.gen_cache(
            memory, memory, type=self.cross_attn.StaticCache)
        return incremental_cache, static_cache


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [decoder_layer] +
            [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        out = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, memory, tgt_mask, memory_mask)
            else:
                out, new_cache = layer(out, memory, tgt_mask, memory_mask,
                                       cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)

    def gen_cache(self, memory, do_zip=False):
        """Per-layer (incremental, static) cache pairs; `do_zip=True`
        transposes to ([incrementals...], [statics...]) (reference
        transformer.py:1060)."""
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation='relu', attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from ...core.tensor import Tensor
        m = jnp.where(jnp.tril(jnp.ones((length, length), bool)), 0.0, -1e9)
        return Tensor(m)
