"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from .. import functional as F
from .layers import Layer

__all__ = ['CrossEntropyLoss', 'BCELoss', 'BCEWithLogitsLoss', 'MSELoss',
           'L1Loss', 'NLLLoss', 'KLDivLoss', 'SmoothL1Loss',
           'MarginRankingLoss', 'CTCLoss', 'HingeEmbeddingLoss',
           'CosineEmbeddingLoss', 'SigmoidFocalLoss']


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction='mean',
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction='mean', name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction='mean', pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class MSELoss(Layer):
    def __init__(self, reduction='mean'):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction='mean', name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction='mean',
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction='mean'):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction='mean', delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction='mean', name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction='mean'):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction='mean', name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction='mean', name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class SigmoidFocalLoss(Layer):
    def __init__(self, alpha=0.25, gamma=2.0, reduction='sum', name=None):
        super().__init__()
        self.alpha = alpha
        self.gamma = gamma
        self.reduction = reduction

    def forward(self, logit, label, normalizer=None):
        return F.sigmoid_focal_loss(logit, label, normalizer, self.alpha,
                                    self.gamma, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (reference: nn/layer/loss.py::HSigmoidLoss):
    O(log C) loss for huge softmaxes via a binary tree over classes;
    default tree built host-side, custom trees via path tables."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        self.is_custom = is_custom
        n_nodes = num_classes - 1 if not is_custom else num_classes
        self.weight = self.create_parameter(
            [max(n_nodes, 1), feature_size], attr=weight_attr)
        self.bias = self.create_parameter(
            [max(n_nodes, 1)], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        if self.is_custom and (path_table is None or path_code is None):
            raise ValueError(
                'HSigmoidLoss(is_custom=True) requires path_table and '
                'path_code at forward (the weight is sized for the '
                'custom tree; the default tree would mis-index it)')
        return F.hsigmoid_loss(
            input, label, self.num_classes, self.weight, self.bias,
            path_table=path_table, path_code=path_code)


__all__ += ['HSigmoidLoss']
