"""Probability distributions: Normal / Uniform / Categorical.

Reference analogue: python/paddle/distribution.py (Distribution, Normal,
Uniform, Categorical).  TPU-native: sampling draws explicit PRNG keys from
core.rng (jax.random), so samples are reproducible under paddle_tpu.seed
and reparameterized (Normal/Uniform are pathwise-differentiable).
"""
import numpy as np
import jax
import jax.numpy as jnp

from .core import rng as _rng
from .core.tensor import Tensor

__all__ = ['Distribution', 'Normal', 'Uniform', 'Categorical',
           'MultivariateNormalDiag']


def _next_key():
    # core.rng.next_key respects both paddle_tpu.seed reseeding and the
    # functional-key scope installed by jit tracing
    return _rng.next_key()


def _val(x):
    if isinstance(x, Tensor):
        return x.value
    return jnp.asarray(x, dtype=jnp.float32)


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(
            self.loc, jnp.broadcast_shapes(self.loc.shape,
                                           self.scale.shape)))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            self.scale ** 2, jnp.broadcast_shapes(self.loc.shape,
                                                  self.scale.shape)))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        eps = jax.random.normal(_next_key(), shape + base,
                                dtype=jnp.float32)
        return Tensor(self.loc + self.scale * eps)

    def entropy(self):
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        scale = jnp.broadcast_to(self.scale, base)
        return Tensor(0.5 + 0.5 * np.log(2 * np.pi) + jnp.log(scale))

    def log_prob(self, value):
        v = _val(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * np.log(2 * np.pi))

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value).value))

    def kl_divergence(self, other):
        # KL(N0 || N1) elementwise over broadcast shapes
        var0, var1 = self.scale ** 2, other.scale ** 2
        t1 = (self.loc - other.loc) ** 2 / (2 * var1)
        t2 = var0 / (2 * var1)
        return Tensor(t1 + t2 - 0.5 + jnp.log(other.scale / self.scale))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        base = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        u = jax.random.uniform(_next_key(), shape + base,
                               dtype=jnp.float32)
        return Tensor(self.low + (self.high - self.low) * u)

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))

    def log_prob(self, value):
        v = _val(value)
        inside = (v >= self.low) & (v < self.high)
        dens = 1.0 / (self.high - self.low)
        return Tensor(jnp.where(inside, jnp.log(dens), -jnp.inf))

    def probs(self, value):
        v = _val(value)
        inside = (v >= self.low) & (v < self.high)
        return Tensor(jnp.where(inside, 1.0 / (self.high - self.low), 0.0))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _val(logits)

    def _log_pmf(self):
        return self.logits - jax.scipy.special.logsumexp(
            self.logits, axis=-1, keepdims=True)

    def sample(self, shape=()):
        shape = tuple(shape)
        return Tensor(jax.random.categorical(
            _next_key(), self.logits, shape=shape + self.logits.shape[:-1]))

    def entropy(self):
        logp = self._log_pmf()
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, axis=-1))

    def log_prob(self, value):
        v = _val(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            self._log_pmf(), v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value).value))

    def kl_divergence(self, other):
        logp = self._log_pmf()
        logq = other._log_pmf()
        return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1))


class MultivariateNormalDiag(Distribution):
    """Multivariate normal with a positive-definite DIAGONAL
    covariance matrix (reference
    fluid/layers/distributions.py:531 — like it, only `entropy` and
    `kl_divergence` are defined).

    Args:
        loc: mean vector [k].
        scale: diagonal covariance matrix [k, k] (off-diagonal zero).
    """

    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)

    # everything reduces to the diagonal vector; log-det is a SUM of
    # logs (the reference's prod-then-log determinant underflows f32
    # to -inf around k~60 at variance 0.1 — a deliberate improvement)
    @staticmethod
    def _diag(mat):
        return jnp.diagonal(mat)

    def entropy(self):
        diag = self._diag(self.scale)
        k = diag.shape[0]
        return Tensor(0.5 * (k * (1.0 + np.log(2 * np.pi))
                             + jnp.sum(jnp.log(diag))))

    def kl_divergence(self, other):
        if not isinstance(other, MultivariateNormalDiag):
            raise TypeError('kl_divergence expects another '
                            'MultivariateNormalDiag, got '
                            f'{type(other).__name__}')
        ds, do = self._diag(self.scale), self._diag(other.scale)
        d = other.loc - self.loc
        tr = jnp.sum(ds / do)
        tri = jnp.sum(d * d / do)
        k = ds.shape[0]
        ln_cov = jnp.sum(jnp.log(do)) - jnp.sum(jnp.log(ds))
        return Tensor(0.5 * (tr + tri - k + ln_cov))
