"""paddle.hub — model loading by repo/name.

Reference analogue: python/paddle/hub.py (github/gitee/local sources).
This build is zero-egress, so only `source='local'` performs real work;
remote sources raise with a clear message.  A hub repo is a directory
with an `hubconf.py` exposing callables.
"""
import importlib.util
import os

__all__ = ['list', 'help', 'load']

_HUBCONF = 'hubconf.py'


def _load_entry_module(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f'no {_HUBCONF} in {repo_dir}')
    spec = importlib.util.spec_from_file_location('paddle_tpu_hubconf',
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source != 'local':
        raise RuntimeError(
            f'hub source {source!r} needs network egress; this build '
            f"supports source='local' (a directory with hubconf.py)")


def list(repo_dir, source='local', force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_entry_module(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith('_')]


def help(repo_dir, model, source='local', force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_entry_module(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source='local', force_reload=False, **kwargs):
    _check_source(source)
    mod = _load_entry_module(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f'{model!r} not found in {repo_dir}/{_HUBCONF}; '
                         f'available: {list(repo_dir)}')
    return getattr(mod, model)(**kwargs)
