"""paddle.batch — reader decorator (reference python/paddle/batch.py).

Groups samples from a sample-level reader into lists of `batch_size`.
Kept for parity with legacy reader pipelines; new code should use
paddle_tpu.io.DataLoader, which adds collation and C++ prefetch.
"""

__all__ = ['batch']


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batch reader yielding lists."""
    if batch_size <= 0:
        raise ValueError(f'batch_size must be positive, got {batch_size}')

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batch_reader
