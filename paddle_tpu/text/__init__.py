"""paddle_tpu.text (reference: python/paddle/text)."""
from .datasets import (  # noqa: F401
    Imdb, Imikolov, Movielens, UCIHousing, Conll05st, WMT14, WMT16)

__all__ = ['Imdb', 'Imikolov', 'Movielens', 'UCIHousing', 'Conll05st',
           'WMT14', 'WMT16']
