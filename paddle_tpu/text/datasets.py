"""Text datasets: Imdb / Imikolov / Movielens / UCIHousing / Conll05st /
WMT14 / WMT16.

Reference analogue: python/paddle/text/datasets/*.py — each downloads a
corpus from bcebos; this zero-egress build serves deterministic synthetic
corpora with the same per-sample structure (ids/shapes/dtypes), so model
code written against the reference runs unchanged.  When `data_file`
points at a real local corpus in the reference's format, Imdb and
UCIHousing parse it.
"""
import gzip
import os
import re
import string
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ['Imdb', 'Imikolov', 'Movielens', 'UCIHousing', 'Conll05st',
           'WMT14', 'WMT16']


def _rng(seed, mode):
    return np.random.RandomState(seed + (0 if mode == 'train' else 1))


class Imdb(Dataset):
    """(word-id sequence, 0/1 sentiment label)."""

    VOCAB_SIZE = 5147  # synthetic vocab size (reference cutoff-dependent)

    def __init__(self, data_file=None, mode='train', cutoff=150,
                 download=True):
        mode = mode.lower()
        assert mode in ('train', 'test'), \
            "mode should be 'train', 'test', but got {}".format(mode)
        self.mode = mode
        if data_file and os.path.exists(data_file):
            self._load_tar(data_file, cutoff)
        else:
            rng = _rng(501, mode)
            n = 2048 if mode == 'train' else 512
            self.docs, self.labels = [], []
            for _ in range(n):
                label = int(rng.randint(0, 2))
                length = int(rng.randint(8, 120))
                # sentiment-dependent token bias keeps the task learnable
                lo = 0 if label == 0 else self.VOCAB_SIZE // 2
                ids = rng.randint(lo, lo + self.VOCAB_SIZE // 2,
                                  size=length)
                self.docs.append(ids.astype(np.int64))
                self.labels.append(label)
        self.word_idx = {i: i for i in range(self.VOCAB_SIZE)}

    def _load_tar(self, path, cutoff):
        pat_pos = re.compile(r'aclImdb/{}/pos/.*\.txt$'.format(self.mode))
        pat_neg = re.compile(r'aclImdb/{}/neg/.*\.txt$'.format(self.mode))
        freq = {}
        docs_raw = []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                lab = 1 if pat_pos.match(m.name) else \
                    (0 if pat_neg.match(m.name) else None)
                if lab is None:
                    continue
                text = tf.extractfile(m).read().decode('latin-1').lower()
                toks = text.translate(
                    str.maketrans('', '', string.punctuation)).split()
                docs_raw.append((toks, lab))
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c >= cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = len(vocab)
        self.docs = [np.array([self.word_idx.get(t, unk) for t in toks],
                              dtype=np.int64) for toks, _ in docs_raw]
        self.labels = [lab for _, lab in docs_raw]

    def __getitem__(self, idx):
        return self.docs[idx], np.array([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram / sequence language-model samples."""

    def __init__(self, data_file=None, data_type='NGRAM', window_size=-1,
                 mode='train', min_word_freq=50, download=True):
        mode = mode.lower()
        assert mode in ('train', 'test'), \
            "mode should be 'train', 'test', but got {}".format(mode)
        assert data_type.upper() in ('NGRAM', 'SEQ')
        self.data_type = data_type.upper()
        if self.data_type == 'NGRAM':
            assert window_size > 0, 'NGRAM needs window_size > 0'
        self.window_size = window_size
        self.vocab_size = 2074  # reference-scale PTB vocab after cutoff
        rng = _rng(521, mode)
        n_sents = 2048 if mode == 'train' else 256
        self.data = []
        for _ in range(n_sents):
            length = int(rng.randint(4, 24))
            sent = rng.randint(0, self.vocab_size, size=length)
            if self.data_type == 'NGRAM':
                for i in range(window_size - 1, length):
                    self.data.append(tuple(
                        np.int64(sent[i - window_size + 1 + j])
                        for j in range(window_size)))
            else:
                self.data.append(sent.astype(np.int64))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """(user_id, gender, age, job, movie_id, category_vec, title_vec,
    rating) — the Wide&Deep-style sparse-feature sample."""

    NUM_USERS = 6040
    NUM_MOVIES = 3952
    NUM_JOBS = 21
    NUM_AGES = 7
    NUM_CATEGORIES = 18
    TITLE_LEN = 5
    TITLE_VOCAB = 5175

    def __init__(self, data_file=None, mode='train', test_ratio=0.1,
                 rand_seed=0, download=True):
        mode = mode.lower()
        assert mode in ('train', 'test'), \
            "mode should be 'train', 'test', but got {}".format(mode)
        rng = np.random.RandomState(541 + rand_seed
                                    + (0 if mode == 'train' else 1))
        n = 4096 if mode == 'train' else 512
        self.samples = []
        for _ in range(n):
            uid = rng.randint(1, self.NUM_USERS + 1)
            gender = rng.randint(0, 2)
            age = rng.randint(0, self.NUM_AGES)
            job = rng.randint(0, self.NUM_JOBS)
            mid = rng.randint(1, self.NUM_MOVIES + 1)
            cat = rng.randint(0, self.NUM_CATEGORIES,
                              size=rng.randint(1, 4))
            title = rng.randint(0, self.TITLE_VOCAB, size=self.TITLE_LEN)
            # rating correlates with (uid+mid) parity so embeddings learn
            rating = float((uid + mid + gender) % 5 + 1)
            self.samples.append(
                (np.int64(uid), np.int64(gender), np.int64(age),
                 np.int64(job), np.int64(mid), cat.astype(np.int64),
                 title.astype(np.int64),
                 np.array([rating], dtype=np.float32)))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class UCIHousing(Dataset):
    """(13 float features, house price)."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode='train', download=True):
        mode = mode.lower()
        assert mode in ('train', 'test'), \
            "mode should be 'train' or 'test', but got {}".format(mode)
        self.mode = mode
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file)
            feats, prices = raw[:, :-1], raw[:, -1:]
            feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
            split = int(len(raw) * 0.8)
            if mode == 'train':
                self.data = feats[:split].astype(np.float32)
                self.label = prices[:split].astype(np.float32)
            else:
                self.data = feats[split:].astype(np.float32)
                self.label = prices[split:].astype(np.float32)
        else:
            rng = _rng(561, mode)
            n = 404 if mode == 'train' else 102  # reference split sizes
            self.data = rng.randn(n, self.FEATURE_DIM).astype(np.float32)
            w = np.linspace(-2, 2, self.FEATURE_DIM).astype(np.float32)
            noise = rng.randn(n).astype(np.float32) * 0.1
            self.label = (self.data @ w + 22.0 + noise)[:, None]

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """SRL sequences: (pred_idx, mark, word_ids..., label_ids)."""

    WORD_VOCAB = 44068
    LABEL_NUM = 67
    PRED_VOCAB = 3162

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode='train',
                 download=True):
        rng = _rng(581, mode if mode in ('train', 'test') else 'train')
        n = 1024
        self.samples = []
        for _ in range(n):
            length = int(rng.randint(5, 40))
            words = rng.randint(0, self.WORD_VOCAB, size=length)
            pred = rng.randint(0, self.PRED_VOCAB)
            pred_pos = rng.randint(0, length)
            mark = np.zeros(length, dtype=np.int64)
            mark[pred_pos] = 1
            labels = rng.randint(0, self.LABEL_NUM, size=length)
            ctx = [words[max(0, min(length - 1, pred_pos + d))]
                   for d in (-2, -1, 0, 1, 2)]
            self.samples.append(
                tuple([words.astype(np.int64)]
                      + [np.full(length, c, dtype=np.int64) for c in ctx]
                      + [np.full(length, pred, dtype=np.int64), mark,
                         labels.astype(np.int64)]))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class _WMTBase(Dataset):
    """(src_ids, trg_ids, trg_ids_next) translation triples."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, seed, mode, dict_size):
        rng = _rng(seed, mode)
        self.dict_size = dict_size
        n = 2048 if mode == 'train' else 256
        self.samples = []
        for _ in range(n):
            slen = int(rng.randint(3, 30))
            tlen = int(rng.randint(3, 30))
            src = rng.randint(3, dict_size, size=slen).astype(np.int64)
            trg_core = rng.randint(3, dict_size, size=tlen).astype(np.int64)
            trg = np.concatenate([[self.BOS], trg_core]).astype(np.int64)
            trg_next = np.concatenate([trg_core,
                                       [self.EOS]]).astype(np.int64)
            self.samples.append((src, trg, trg_next))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(_WMTBase):
    def __init__(self, data_file=None, mode='train', dict_size=30000,
                 download=True):
        mode = mode.lower()
        assert mode in ('train', 'test', 'gen'), \
            "mode should be 'train', 'test' or 'gen', got {}".format(mode)
        super().__init__(601, 'train' if mode == 'train' else 'test',
                         dict_size)
        self.mode = mode

    def get_dict(self, reverse=False):
        d = {i: 'w{}'.format(i) for i in range(self.dict_size)}
        return ({v: k for k, v in d.items()} if reverse else d,) * 2


class WMT16(_WMTBase):
    def __init__(self, data_file=None, mode='train', src_dict_size=-1,
                 trg_dict_size=-1, lang='en', download=True):
        mode = mode.lower()
        assert mode in ('train', 'test', 'val'), \
            "mode should be 'train', 'test' or 'val', got {}".format(mode)
        size = src_dict_size if src_dict_size > 0 else 30000
        super().__init__(621, 'train' if mode == 'train' else 'test', size)
        self.mode = mode
        self.lang = lang

    def get_dict(self, lang='en', reverse=False):
        d = {i: 'w{}'.format(i) for i in range(self.dict_size)}
        return {v: k for k, v in d.items()} if reverse else d
