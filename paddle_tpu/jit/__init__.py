"""paddle_tpu.jit — dy2static: compile dygraph code into ONE XLA module.

Reference analogue: /root/reference/python/paddle/jit/ (to_static /
ProgramTranslator in dy2static/program_translator.py, jit.save/load in
jit.py + TranslatedLayer).  The reference rewrites Python AST into a
static ProgramDesc executed op-by-op; TPU-native we instead *functionally
capture* the Layer — parameters/buffers become pytree inputs, the global
RNG becomes an explicit threaded PRNGKey — and hand the pure function to
jax.jit, so the whole forward (or train step) compiles to a single
fused StableHLO module.  save/load round-trips through jax.export
serialization (our StableHLO stand-in for the reference's saved
ProgramDesc + params).
"""
import functools
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import rng as rng_mod
from ..core.autograd import no_grad
from ..core.dtype import convert_dtype
from ..nn.layer.layers import Layer
from . import dy2static
from .dy2static import convert_control_flow

__all__ = ['to_static', 'not_to_static', 'save', 'load', 'functional_call',
           'TranslatedLayer', 'StaticFunction', 'enable_to_static',
           'dy2static']

_to_static_enabled = True


def enable_to_static(flag):
    """ProgramTranslator().enable(...) analogue — globally toggle."""
    global _to_static_enabled
    _to_static_enabled = bool(flag)


def _unwrap(x):
    return x.value if isinstance(x, Tensor) else x


def _wrap_out(out):
    if isinstance(out, (tuple, list)):
        return type(out)(_wrap_out(o) for o in out)
    if isinstance(out, Tensor):
        return out
    return Tensor._from_value(out)


def _flatten_out(out):
    """Layer outputs (Tensor | tuple/list of Tensors, nested) → raw pytree."""
    if isinstance(out, (tuple, list)):
        return type(out)(_flatten_out(o) for o in out)
    if isinstance(out, dict):
        return {k: _flatten_out(v) for k, v in out.items()}
    return _unwrap(out)


def _rewrap_out(vals):
    if isinstance(vals, (tuple, list)):
        return type(vals)(_rewrap_out(v) for v in vals)
    if isinstance(vals, dict):
        return {k: _rewrap_out(v) for k, v in vals.items()}
    return Tensor._from_value(vals)


def functional_call(layer, params, buffers, args, kwargs=None, key=None,
                    training=None):
    """Run `layer` as a pure function of (params, buffers, key, *args).

    Returns (raw outputs pytree, new_buffers dict).  Safe to call inside a
    jax trace: live eager state is swapped out and restored.  This is the
    TPU-native replacement for the reference's ProgramDesc capture.
    """
    kwargs = kwargs or {}
    old_params, old_buffers = layer.functional_state()
    old_training = layer.training
    if training is not None:
        layer.train() if training else layer.eval()
    layer.load_functional_state(params, buffers)
    try:
        scope = rng_mod.functional_key_scope(
            key if key is not None else jax.random.PRNGKey(0))
        with no_grad(), scope:
            out = layer(*[Tensor._from_value(a) if not isinstance(a, Tensor)
                          else a for a in args], **kwargs)
        new_buffers = {n: b.value for n, b in layer.named_buffers()}
        return _flatten_out(out), new_buffers
    finally:
        layer.load_functional_state(old_params, old_buffers)
        layer.train() if old_training else layer.eval()


class StaticFunction:
    """The callable produced by @to_static.

    jax.jit caches compiled modules by input shape/dtype; Python-level
    (non-Tensor) arguments are closed over and keyed in our own cache,
    mirroring how the reference re-traces per input signature
    (dy2static/program_translator.py::StaticFunction).
    """

    def __init__(self, dygraph_function, input_spec=None, build_strategy=None,
                 backend=None, check=None):
        self._dygraph_function = dygraph_function
        self._input_spec = input_spec
        self._layer = dygraph_function if isinstance(dygraph_function, Layer) \
            else None
        self._jitted = {}          # static-key -> jitted fn
        self._check = check        # analysis lint mode: None/'warn'/'error'
        self._last_lowered = None  # for save()
        # forward the USER callable's identity (the reference's
        # StaticFunction does the same); for a wrapped Layer that is
        # the layer's forward, not the internal _BoundForward adapter
        src = dygraph_function
        if isinstance(src, _BoundForward):
            src = type(src._inner).forward
        functools.update_wrapper(
            self, src,
            assigned=('__name__', '__qualname__', '__doc__',
                      '__module__'),
            updated=())

    @property
    def dygraph_function(self):
        return self._dygraph_function

    def _split_args(self, args):
        tpos, tvals, static = [], [], []
        for i, a in enumerate(args):
            if isinstance(a, (Tensor, jax.Array, np.ndarray)):
                tpos.append(i)
                tvals.append(_unwrap(a) if isinstance(a, Tensor)
                             else jnp.asarray(a))
            else:
                static.append((i, a))
        return tuple(tpos), tvals, tuple(static)

    def _make_pure(self, tpos, static, n_args, training):
        layer = self._layer
        # data-dependent `if`/`while` in the source lower to
        # lax.cond/lax.while_loop (no-op for unconvertible functions)
        fn = convert_control_flow(self._dygraph_function) \
            if layer is None else self._dygraph_function

        def pure(params, buffers, key, tvals):
            full = [None] * n_args
            for (i, a) in static:
                full[i] = a
            for i, v in zip(tpos, tvals):
                full[i] = v
            if layer is not None:
                return functional_call(layer, params, buffers, full,
                                       key=key, training=training)
            scope = rng_mod.functional_key_scope(key)
            with no_grad(), scope:
                out = fn(*[Tensor._from_value(v) if isinstance(v, jax.Array)
                           else v for v in full])
            return _flatten_out(out), {}

        return pure

    def _make_jitted(self, tpos, static, n_args, training):
        return jax.jit(self._make_pure(tpos, static, n_args, training))

    def _check_report(self, tpos, static, n_args, training, params,
                      buffers, key, tvals):
        """to_static(check=...): lint the exact pure function jax.jit
        will compile for this signature, plus the AST of the user's
        source; python-scalar static args are the retrace hazards."""
        from .. import analysis
        pure = self._make_pure(tpos, static, n_args, training)
        report = analysis.lint(pure, params, buffers, key, tvals,
                               name=getattr(self, '__name__', 'to_static'),
                               source=False)
        # scalars the StaticFunction cache closes over as static values
        # — same hazard, same shared policy as the jaxpr rule
        scalars = [(i, a) for (i, a) in static
                   if isinstance(a, (bool, int, float))]
        report.findings.extend(analysis.scalar_arg_findings(
            scalars, self.__name__))
        # active mesh -> escalate to the lowered-HLO SPMD audit:
        # state replicated, traced tensors sharded on the first data
        # axis when divisible (analysis.hlo's forced-mesh heuristic)
        from ..distributed import env as _env
        mesh = _env.get_mesh()
        if mesh is not None:
            analysis.escalate_hlo(
                report, pure, (params, buffers, key), (tvals,), mesh,
                name=getattr(self, '__name__', 'to_static'))
        src_fn = self._dygraph_function
        if isinstance(src_fn, _BoundForward):
            src_fn = type(src_fn._inner).forward
        elif isinstance(src_fn, Layer):
            src_fn = type(src_fn).forward
        report.extend(analysis.lint_callable(src_fn))
        return report

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._dygraph_function(*args, **kwargs)
        if kwargs:
            # keyword tensors are not traced positionally; keep it simple
            # and fall back to eager for kwarg-style calls.
            return self._dygraph_function(*args, **kwargs)
        tpos, tvals, static = self._split_args(args)
        training = self._layer.training if self._layer is not None else False
        cache_key = (tpos, tuple(repr(s) for s in static), len(args),
                     training)
        params, buffers = (self._layer.functional_state()
                           if self._layer is not None else ({}, {}))
        key = rng_mod.next_key()
        if cache_key not in self._jitted:
            if self._check:
                from .. import analysis
                analysis.safe_emit(
                    lambda: self._check_report(
                        tpos, static, len(args), training, params,
                        buffers, key, tvals),
                    self._check)
            jitted = self._make_jitted(tpos, static, len(args), training)
            from ..core import compile_cache as _cc
            if _cc.enabled():
                # persistent executable cache: a warm process (restart,
                # second worker, inference cold-start) deserializes the
                # exported module instead of re-tracing; the cold path
                # below keeps today's exact jit (and exports it)
                fp = _cc.jaxpr_fingerprint(
                    'to_static',
                    self._make_pure(tpos, static, len(args), training),
                    (params, buffers, key, tvals))
                jitted = _cc.through_cache(
                    jitted, (params, buffers, key, tvals), fp=fp,
                    name=f'to_static({self.__name__})')
            # memory observatory, armed-only (one extra lower+compile
            # per variant): XLA memory_analysis vs liveness prediction
            from ..telemetry import memory as _mem
            if _mem.armed():
                _mem.maybe_note_compiled(
                    f'to_static({self.__name__})', jitted,
                    (params, buffers, key, tvals), source='to_static')
            self._jitted[cache_key] = jitted
            # the retrace monitor: many signature variants on one
            # StaticFunction means something in the signature is
            # unstable (shapes / scalars / weak types)
            from ..analysis import note_retrace
            note_retrace(f'to_static({self.__name__})',
                         len(self._jitted), instance=self)
        out_vals, new_buffers = self._jitted[cache_key](
            params, buffers, key, tvals)
        if self._layer is not None and new_buffers:
            self._layer.load_functional_state(buffers=new_buffers)
        self._last_call = (cache_key, tpos, static, len(args), training)
        return _rewrap_out(out_vals)

    # -- export --------------------------------------------------------------
    def _structs_from_spec(self, input_spec):
        """InputSpecs → ShapeDtypeStructs; None/-1 dims become jax.export
        symbolic dimensions so the serialized module stays batch-dynamic
        (the reference's saved ProgramDesc is shape-polymorphic too)."""
        from jax import export as jexport
        structs = []
        sym_i = 0
        for s in input_spec:
            parts = []
            for d in s.shape:
                if d is None or d == -1:
                    parts.append(f"b{sym_i}")
                    sym_i += 1
                else:
                    parts.append(str(d))
            dtype = convert_dtype(s.dtype) or jnp.float32
            if sym_i:
                shape = jexport.symbolic_shape(','.join(parts))
            else:
                shape = tuple(int(p) for p in parts)
            structs.append(jax.ShapeDtypeStruct(shape, dtype))
        return structs

    def exported(self, input_spec):
        """jax.export the eval-mode forward for the given spec."""
        structs = self._structs_from_spec(input_spec)
        n = len(structs)
        tpos = tuple(range(n))
        jitted = self._make_jitted(tpos, (), n, training=False)
        params, buffers = (self._layer.functional_state()
                           if self._layer is not None else ({}, {}))
        key = jax.random.PRNGKey(0)
        from jax import export as jexport
        p_structs = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), params)
        b_structs = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), buffers)
        exp = jexport.export(jitted)(p_structs, b_structs, key, structs)
        return exp


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, check=None, **kwargs):
    """Decorator/wrapper: compile a function or Layer with XLA.

    Reference: python/paddle/jit/api.py::to_static.

    check: run the paddle_tpu.analysis TPU lint over the traced
    function on each first-compile of a signature — None/False (off),
    'warn'/True (findings surface as LintWarning), 'error' (raise
    LintError on high-severity findings).  See README "Linting your
    model".
    """
    def decorate(fn):
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(_BoundForward(fn), input_spec,
                                        check=check)
            # calling the layer itself routes through forward, which is
            # now compiled; also expose the StaticFunction
            fn._static_forward = fn.forward
            return fn
        return StaticFunction(fn, input_spec, check=check)

    if function is not None:
        return decorate(function)
    return decorate


class _BoundForward(Layer):
    """Adapter: present a Layer's forward as the traced callable while
    sharing its parameter tree."""

    def __init__(self, layer):
        super().__init__()
        self._inner = layer

    def forward(self, *args, **kwargs):
        fwd = convert_control_flow(type(self._inner).forward)
        return fwd(self._inner, *args, **kwargs)

    # state delegation so functional capture sees the real tree
    def named_parameters(self, prefix='', include_sublayers=True):
        return self._inner.named_parameters(prefix, include_sublayers)

    def named_buffers(self, prefix='', include_sublayers=True):
        return self._inner.named_buffers(prefix, include_sublayers)

    def functional_state(self):
        return self._inner.functional_state()

    def load_functional_state(self, params=None, buffers=None):
        return self._inner.load_functional_state(params, buffers)

    @property
    def training(self):
        return self._inner.training

    @training.setter
    def training(self, v):
        # Layer.__init__ writes this before _inner exists
        if '_inner' in self.__dict__ or '_inner' in self.__dict__.get(
                '_sub_layers', {}):
            self._inner.training = v

    def train(self):
        self._inner.train()

    def eval(self):
        self._inner.eval()


def not_to_static(fn):
    """Marker no-op (reference: paddle.jit.not_to_static)."""
    fn._not_to_static = True
    return fn


# -- save / load -------------------------------------------------------------

def save(layer, path, input_spec=None, **configs):
    """jit.save — serialize compiled forward + params.

    Reference: python/paddle/jit/api.py::save writes __model__ ProgramDesc
    + params; we write <path>.pdmodel (jax.export serialized StableHLO)
    and <path>.pdiparams (pickled state).
    """
    from ..static.input_spec import InputSpec

    if isinstance(layer, StaticFunction):
        static_fn = layer
        base = static_fn._layer
    elif isinstance(layer, Layer):
        fwd = getattr(layer, '_static_forward', None)
        static_fn = fwd if isinstance(fwd, StaticFunction) else \
            StaticFunction(_BoundForward(layer))
        base = layer
    else:
        raise TypeError("jit.save expects a Layer or StaticFunction")

    if input_spec is None:
        raise ValueError("jit.save requires input_spec in this framework "
                         "(shapes define the XLA module)")
    spec = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
            for s in input_spec]
    named = [s.name for s in spec if s.name]
    if len(named) != len(set(named)):
        raise ValueError(
            f'jit.save: duplicate InputSpec names {sorted(named)} — '
            'deployments feed inputs by name, so names must be unique')
    exp = static_fn.exported(spec)
    blob = exp.serialize()
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    with open(path + '.pdmodel', 'wb') as f:
        f.write(blob)
    state = {}
    if base is not None:
        params, buffers = base.functional_state()
        state = {'params': {k: np.asarray(v) for k, v in params.items()},
                 'buffers': {k: np.asarray(v) for k, v in buffers.items()}}
    with open(path + '.pdiparams', 'wb') as f:
        pickle.dump({'state': state,
                     'spec': [(s.shape, str(np.dtype(s.numpy_dtype()))
                               if s.numpy_dtype() else 'float32', s.name)
                              for s in spec]}, f)


class TranslatedLayer(Layer):
    """jit.load result — a Layer whose forward executes the deserialized
    XLA module (reference: translated_layer.py runs the loaded
    ProgramDesc)."""

    def __init__(self, exported, state, input_specs=None):
        super().__init__()
        self._exported = exported
        self._params_tree = {k: jnp.asarray(v)
                             for k, v in state.get('params', {}).items()}
        self._buffers_tree = {k: jnp.asarray(v)
                              for k, v in state.get('buffers', {}).items()}
        # (shape, dtype, name) tuples pickled by jit.save — real tensor
        # names so deployments (inference.Predictor) can feed by name
        self._input_specs = input_specs or []

    def input_names(self):
        return [n or f'input_{i}'
                for i, (_, _, n) in enumerate(self._input_specs)]

    def forward(self, *args):
        tvals = [_unwrap(a) for a in args]
        out_vals, _ = self._exported.call(
            self._params_tree, self._buffers_tree, jax.random.PRNGKey(0),
            tvals)
        return _rewrap_out(out_vals)


def load(path, **configs):
    from jax import export as jexport
    with open(path + '.pdmodel', 'rb') as f:
        exp = jexport.deserialize(f.read())
    with open(path + '.pdiparams', 'rb') as f:
        meta = pickle.load(f)
    return TranslatedLayer(exp, meta['state'], meta.get('spec'))


# -- dy2static compat surface -------------------------------------------------

class ProgramTranslator:
    """Reference dy2static/program_translator.py::ProgramTranslator — a
    process-wide singleton whose enable() toggles dy2static.  Here the
    translation IS functional capture + jax.jit, so the singleton only
    carries the global enable flag (enable_to_static)."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static_flag):
        enable_to_static(enable_to_static_flag)

    @property
    def enable_to_static(self):
        return _to_static_enabled

    def get_code(self, dygraph_func):
        import inspect
        # no source-to-source rewrite happens: the traced source IS the code
        return inspect.getsource(dygraph_func)

    def get_func(self, dygraph_func):
        return StaticFunction(dygraph_func)


_verbosity = 0


def set_verbosity(level=0, also_to_stdout=False):
    """Reference dy2static logging_utils.set_verbosity: configure the
    translation logger.  Tracing here has one phase, so this sets the
    module logger level (DEBUG when level>0)."""
    import logging
    global _verbosity
    _verbosity = int(level)
    logger = logging.getLogger('paddle_tpu.jit')
    logger.setLevel(logging.DEBUG if level > 0 else logging.WARNING)
    if also_to_stdout and not logger.handlers:
        logger.addHandler(logging.StreamHandler())
    return _verbosity


def set_code_level(level=100, also_to_stdout=False):
    """Reference dy2static set_code_level: print transformed code at a
    given pass.  There is no AST pipeline here; this enables the same
    logger as set_verbosity (the "code" is the jaxpr, fetchable via
    jax.make_jaxpr on the captured function)."""
    return set_verbosity(1 if level else 0, also_to_stdout)


class TracedLayer:
    """Reference fluid/dygraph/jit.py::TracedLayer — trace a dygraph
    Layer with example inputs into a static inference function.

    TracedLayer.trace(layer, inputs) runs the layer once, pins the input
    specs, and returns (outputs, traced); traced(inputs...) replays the
    compiled XLA module and traced.save_inference_model(path) writes the
    self-contained StableHLO artifact (loadable with jit.load or
    static.load_inference_model).
    """

    def __init__(self, layer, static_fn, input_spec):
        self._layer = layer
        self._static_fn = static_fn
        self._input_spec = input_spec

    @staticmethod
    def trace(layer, inputs):
        from ..static.input_spec import InputSpec
        inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
            else [inputs]
        out = layer(*inputs)
        spec = [InputSpec.from_tensor(t if isinstance(t, Tensor)
                                      else Tensor(t)) for t in inputs]
        sf = StaticFunction(_BoundForward(layer))
        return out, TracedLayer(layer, sf, spec)

    def __call__(self, inputs):
        inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
            else [inputs]
        out = self._static_fn(*inputs)
        return out if isinstance(out, (list, tuple)) else [out]

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        if isinstance(path, (list, tuple)):  # legacy (dirname, ...) form
            path = path[0]
        save(self._static_fn, path, input_spec=self._input_spec)


__all__ += ['ProgramTranslator', 'set_verbosity', 'set_code_level',
            'TracedLayer']
