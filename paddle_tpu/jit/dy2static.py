"""dy2static control-flow conversion: Python `if`/`while` on traced
tensors -> `lax.cond` / `lax.while_loop`.

Reference analogue:
/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
convert_operators.py:25,190 (`convert_while_loop`, `convert_ifelse`) and
ast_transformer.py (the AST rewrite pass behind ProgramTranslator).
There the rewrite emits cond/while ops into a ProgramDesc; TPU-native
the same source rewrite emits `jax.lax.cond` / `jax.lax.while_loop`
calls, which XLA compiles to on-device control flow — no host round
trips, fully inside the jitted module.

Semantics contract (mirrors the reference's converted operators):
- a predicate that is a CONCRETE Python/numpy/jax value executes the
  taken branch as plain Python — zero behavior change for static
  control flow (`if self.training: ...`);
- a predicate that is a traced tensor lowers to lax.cond/while_loop;
  both branches then trace, and every variable assigned in either
  branch must produce matching shapes/dtypes (the reference imposes
  the same through its merge of branch outputs into select ops).

Supported rewrites: `if`/`elif`/`else` (including branches that
`return`, with the statement tail folded into the implicit else),
`while` — including `break`/`continue`, desugared into carried/local
flags folded into the loop condition and lax.cond guards (matching the
reference's convert_while_loop flag technique at
convert_operators.py:25) — `for ... in range(...)` (desugared to a
counter while; tensor bounds lower to lax.while_loop, literal steps
only), and `and`/`or`/`not` inside the tests.
Unsupported (the transformer bails out and the function runs with plain
tracing, which is exactly the pre-conversion behavior): `return` inside
a converted `while`, `break`/`continue` under with/try inside a
converted while, closures over free variables, and sources `inspect`
cannot retrieve.
"""
import ast
import functools
import inspect
import textwrap
import types

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['convert_ifelse', 'convert_while_loop', 'convert_logical_and',
           'convert_logical_or', 'convert_logical_not',
           'convert_control_flow', 'UNDEFINED']


class _Undefined:
    """Placeholder for names not yet bound when a converted block runs
    (the reference uses UndefinedVar).  Any use raises a clear error."""

    def __repr__(self):
        return '<undefined variable>'

    def _die(self, *a, **k):
        raise NameError(
            'variable used before assignment inside converted control '
            'flow (assign it on every path before use)')

    __getattr__ = __call__ = __add__ = __radd__ = __mul__ = _die
    __bool__ = __iter__ = _die


UNDEFINED = _Undefined()


def _is_tensor(x):
    from ..core.tensor import Tensor
    return isinstance(x, Tensor)


def _raw(x):
    from ..core.tensor import Tensor
    return x.value if isinstance(x, Tensor) else x


def _is_traced(x):
    x = _raw(x)
    return isinstance(x, jax.core.Tracer)


def _unwrap_tree(tree):
    """Tensors -> jax arrays; python numbers -> jnp scalars (so they can
    be loop carries); UNDEFINED flagged by the caller."""
    from ..core.tensor import Tensor

    def leaf(v):
        if isinstance(v, Tensor):
            return v.value
        if isinstance(v, (bool, int, float, np.ndarray, np.generic)):
            return jnp.asarray(v)
        return v

    return jax.tree_util.tree_map(leaf, tree,
                                  is_leaf=lambda v: isinstance(v, Tensor))


def _wrap_tree(tree):
    from ..core.tensor import Tensor
    return jax.tree_util.tree_map(
        lambda v: Tensor._from_value(v) if isinstance(v, jax.Array) else v,
        tree)


def _check_defined(tree, where):
    leaves = jax.tree_util.tree_leaves(
        tree, is_leaf=lambda v: v is UNDEFINED)
    if any(v is UNDEFINED for v in leaves):
        raise ValueError(
            f'converted {where}: every variable carried through tensor '
            'control flow must be assigned before it and on every '
            'branch (found an unassigned one)')


def grab(local_ns, names):
    """Fetch possibly-unbound locals for branch-function arguments."""
    return tuple(local_ns.get(n, UNDEFINED) for n in names)


def convert_ifelse(pred, true_fn, false_fn, args=()):
    """`if pred: ... else: ...` -> lax.cond when pred is traced.

    true_fn/false_fn take *args (the variables either branch assigns)
    and return the tuple of their final values."""
    p = _raw(pred)
    if not _is_traced(p):
        return true_fn(*args) if p else false_fn(*args)
    p = jnp.asarray(p)
    if p.ndim:
        p = p.reshape(())  # single-element tensors act as scalars

    def branch(fn):
        def run(_):
            out = fn(*args)
            _check_defined(out, 'if/else')
            return _unwrap_tree(out)
        return run

    out = jax.lax.cond(p.astype(jnp.bool_), branch(true_fn),
                       branch(false_fn), None)
    return _wrap_tree(out)


def convert_while_loop(cond_fn, body_fn, loop_vars):
    """`while cond: body` -> lax.while_loop when cond traces.

    cond_fn/body_fn take *loop_vars; body_fn returns their new values.
    Vars still UNDEFINED at entry are loop-local temporaries: they are
    recomputed inside each iteration and are NOT part of the lax carry
    (reading one after the loop, or across iterations before
    reassignment, raises — the reference's UndefinedVar does the same)."""
    pred0 = _raw(cond_fn(*loop_vars))
    if not _is_traced(pred0):
        while pred0:
            loop_vars = body_fn(*loop_vars)
            pred0 = _raw(cond_fn(*loop_vars))
        return loop_vars
    carried = [i for i, v in enumerate(loop_vars) if v is not UNDEFINED]
    n = len(loop_vars)

    def full(vs):
        out = [UNDEFINED] * n
        for slot, v in zip(carried, _wrap_tree(vs)):
            out[slot] = v
        return out

    init = _unwrap_tree(tuple(loop_vars[i] for i in carried))

    def cond(vs):
        p = _raw(cond_fn(*full(vs)))
        p = jnp.asarray(p)
        return p.reshape(()).astype(jnp.bool_) if p.ndim else \
            p.astype(jnp.bool_)

    def body(vs):
        out = body_fn(*full(vs))
        picked = tuple(out[i] for i in carried)
        _check_defined(picked, 'while')
        return _unwrap_tree(picked)

    res = _wrap_tree(jax.lax.while_loop(cond, body, init))
    final = [UNDEFINED] * n
    for slot, v in zip(carried, res):
        final[slot] = v
    return tuple(final)


def convert_logical_and(x_fn, y_fn):
    x = x_fn()
    if not _is_traced(x):
        return y_fn() if _raw(x) else x
    y = y_fn()  # traced: both sides evaluate (no data-dependent skip)
    return _wrap_tree(jnp.logical_and(jnp.asarray(_raw(x)),
                                      jnp.asarray(_raw(y))))


def convert_logical_or(x_fn, y_fn):
    x = x_fn()
    if not _is_traced(x):
        return x if _raw(x) else y_fn()
    y = y_fn()
    return _wrap_tree(jnp.logical_or(jnp.asarray(_raw(x)),
                                     jnp.asarray(_raw(y))))


def convert_logical_not(x):
    if not _is_traced(x):
        return not _raw(x)
    return _wrap_tree(jnp.logical_not(jnp.asarray(_raw(x))))


# -- AST rewrite -------------------------------------------------------------

class _Unsupported(Exception):
    pass


class _StoreCollector(ast.NodeVisitor):
    """Names assigned within a statement block (not descending into
    nested function/class definitions)."""

    def __init__(self):
        self.names = []

    def _add(self, name):
        if name not in self.names:
            self.names.append(name)

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self._add(node.id)

    def visit_FunctionDef(self, node):
        self._add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._add(node.name)

    def visit_Lambda(self, node):
        pass  # own scope


def _stores(stmts):
    c = _StoreCollector()
    for s in stmts:
        c.visit(s)
    return c.names


def _has(stmts, kinds):
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, kinds):
                return True
    return False


def _returns_directly(stmts, kinds=(ast.Return,)):
    """True if the block contains a Return not nested in a def."""
    for s in stmts:
        if isinstance(s, ast.Return):
            return True
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        for node in ast.walk(s):
            if isinstance(node, ast.Return):
                return True
    return False


_JST = '__paddle_tpu_jst__'  # collision-safe module-globals binding


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst(attr):
    return ast.Attribute(value=_name(_JST), attr=attr, ctx=ast.Load())


def _call(func, args=None, keywords=None):
    return ast.Call(func=func, args=args or [], keywords=keywords or [])


def _tuple_of(names, ctx=None):
    return ast.Tuple(elts=[_name(n, ctx or ast.Load()) for n in names],
                     ctx=ctx or ast.Load())


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.n = 0

    def _uid(self):
        self.n += 1
        return self.n

    # tests: a and b / a or b / not a -> converted ops
    def _convert_test(self, node):
        if isinstance(node, ast.BoolOp):
            vals = [self._convert_test(v) for v in node.values]
            fn = ('convert_logical_and'
                  if isinstance(node.op, ast.And) else 'convert_logical_or')
            out = vals[0]
            for v in vals[1:]:
                out = _call(_jst(fn), [
                    ast.Lambda(args=ast.arguments(
                        posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                        kw_defaults=[], kwarg=None, defaults=[]), body=out),
                    ast.Lambda(args=ast.arguments(
                        posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                        kw_defaults=[], kwarg=None, defaults=[]), body=v)])
            return out
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return _call(_jst('convert_logical_not'),
                         [self._convert_test(node.operand)])
        return self.visit(node)

    def _fn_def(self, name, argnames, body):
        return ast.FunctionDef(
            name=name,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=a) for a in argnames],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=body, decorator_list=[], returns=None, type_params=[])

    def _grab_call(self, names):
        return _call(_jst('grab'), [
            _call(_name('locals')),
            ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                      ctx=ast.Load())])

    def visit_If(self, node):
        # handled by _transform_block (needs the statement tail)
        return node

    def visit_While(self, node):
        return node

    def visit_FunctionDef(self, node):
        node.body = self._transform_block(node.body, fn_exit=True)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_For(self, node):
        node.body = self._transform_block(node.body, fn_exit=False)
        node.orelse = self._transform_block(node.orelse, fn_exit=False)
        return node

    # -- for-range desugaring (reference convert_operators.py converts
    # tensor-ranged `for` through the same while machinery) -----------

    @staticmethod
    def _is_range_for(node):
        return (isinstance(node, ast.For)
                and isinstance(node.target, ast.Name)
                and isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == 'range'
                and not node.iter.keywords
                and 1 <= len(node.iter.args) <= 3)

    def _desugar_range_for(self, node):
        """`for i in range(a, b, s): body` -> counter while.

        The increment runs BEFORE the user body so `continue` keeps
        python-for semantics (next item, not an infinite loop); the
        loop variable is assigned from the counter at body entry, so
        it holds the last executed value after the loop, like python.
        The resulting While then converts through _rewrite_while when
        its predicate traces (tensor range bound), or runs as plain
        python when concrete.
        """
        if node.orelse:
            raise _Unsupported('for/else on a converted range loop')
        uid = self._uid()
        it = f'__cf_it_{uid}'
        args = node.iter.args
        if len(args) == 1:
            start, stop, step = ast.Constant(value=0), args[0], 1
        elif len(args) == 2:
            start, stop, step = args[0], args[1], 1
        else:
            start, stop = args[0], args[1]
            s = args[2]
            neg = (isinstance(s, ast.UnaryOp)
                   and isinstance(s.op, ast.USub)
                   and isinstance(s.operand, ast.Constant))
            if isinstance(s, ast.Constant) and isinstance(s.value, int):
                step = s.value
            elif neg and isinstance(s.operand.value, int):
                step = -s.operand.value
            else:
                raise _Unsupported(
                    'range() step must be an integer literal in a '
                    'converted for')
            if step == 0:
                raise _Unsupported('range() step of 0')
        step_const = step if isinstance(step, int) else 1
        cmp_op = ast.Lt() if step_const > 0 else ast.Gt()
        # hoist the stop into a temp evaluated ONCE before the loop —
        # python evaluates range() bounds once, so a body that mutates
        # a variable used in the bound must not change iteration count
        stop_name = f'__cf_stop_{uid}'
        test = ast.Compare(left=_name(it), ops=[cmp_op],
                           comparators=[_name(stop_name)])
        body = [
            ast.Assign(targets=[ast.Name(id=node.target.id,
                                         ctx=ast.Store())],
                       value=_name(it)),
            ast.Assign(targets=[_name(it, ast.Store())],
                       value=ast.BinOp(left=_name(it), op=ast.Add(),
                                       right=ast.Constant(
                                           value=step_const))),
        ] + list(node.body)
        return [
            ast.Assign(targets=[_name(it, ast.Store())], value=start),
            ast.Assign(targets=[_name(stop_name, ast.Store())],
                       value=stop),
            ast.While(test=test, body=body, orelse=[]),
        ]

    def visit_With(self, node):
        node.body = self._transform_block(node.body, fn_exit=False)
        return node

    visit_AsyncWith = visit_With

    def visit_Try(self, node):
        node.body = self._transform_block(node.body, fn_exit=False)
        node.orelse = self._transform_block(node.orelse, fn_exit=False)
        node.finalbody = self._transform_block(node.finalbody,
                                               fn_exit=False)
        for h in node.handlers:
            h.body = self._transform_block(h.body, fn_exit=False)
        return node

    def _rewrite_if(self, node, tail, fn_exit):
        """Rewrite one If; returns (new_stmts, consumed_tail).

        `fn_exit` is True when falling off the end of the current block
        returns from the function (function top level, or a branch of an
        already-return-folded if).  Only there may a partially-returning
        `if` fold the statement tail into its implicit else — inside a
        for/while/with/try body, fall-through continues the block, so
        such an `if` is unconvertible (the whole function falls back)."""
        uid = self._uid()
        test = self._convert_test(node.test)

        body_ret = _returns_directly(node.body)
        else_ret = _returns_directly(node.orelse) if node.orelse else False

        if body_ret or else_ret:
            both = body_ret and else_ret
            if not both and not fn_exit:
                raise _Unsupported(
                    'early return from an `if` inside a loop/with/try')
            # fold the statement tail into the non-returning branch so
            # both end in return; `if p: return X` + tail -> else = tail
            raw_body, raw_else = list(node.body), list(node.orelse)
            consumed = False
            if not both:
                if not node.orelse:
                    raw_else = list(tail)
                elif not body_ret:
                    raw_body = raw_body + list(tail)
                else:
                    raw_else = raw_else + list(tail)
                consumed = True
            # params must cover everything either branch (incl. folded
            # tail) assigns, or reassignments hit UnboundLocalError
            stores = sorted(set(_stores(raw_body) + _stores(raw_else)))
            body = self._transform_block(raw_body, fn_exit=True)
            orelse = self._transform_block(raw_else, fn_exit=True)
            if not body or not _returns_directly(body):
                body = body + [ast.Return(value=ast.Constant(value=None))]
            if not orelse or not _returns_directly(orelse):
                orelse = orelse + [ast.Return(value=ast.Constant(value=None))]
            tname, fname = f'__cf_true_{uid}', f'__cf_false_{uid}'
            stmts = [
                self._fn_def(tname, stores, body),
                self._fn_def(fname, stores, orelse),
                ast.Return(value=_call(_jst('convert_ifelse'), [
                    test, _name(tname), _name(fname),
                    self._grab_call(stores)])),
            ]
            return stmts, consumed

        body = self._transform_block(node.body, fn_exit=False)
        orelse = self._transform_block(node.orelse, fn_exit=False)
        stores = sorted(set(_stores(node.body) + _stores(node.orelse)))
        if not stores:
            # pure side-effect-free branches (e.g. asserts) — keep as-is
            node.test = test
            node.body = body
            node.orelse = orelse
            return [node], False
        tname, fname = f'__cf_true_{uid}', f'__cf_false_{uid}'
        ret = ast.Return(value=_tuple_of(stores))
        stmts = [
            self._fn_def(tname, stores, body + [ret]),
            self._fn_def(fname, stores,
                         (orelse or [ast.Pass()]) + [ast.Return(
                             value=_tuple_of(stores))]),
            ast.Assign(
                targets=[_tuple_of(stores, ast.Store())],
                value=_call(_jst('convert_ifelse'), [
                    test, _name(tname), _name(fname),
                    self._grab_call(stores)])),
        ]
        return stmts, False

    # -- break/continue desugaring (reference convert_operators.py:25
    # handles these through while-op flags; same flag technique here) --

    @staticmethod
    def _contains_bc(node):
        """break/continue belonging to THIS loop level (not descending
        into nested loops or function definitions)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.While, ast.For, ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda,
                                  ast.ClassDef)):
                continue
            if isinstance(child, (ast.Break, ast.Continue)) \
                    or _ControlFlowTransformer._contains_bc(child):
                return True
        return False

    def _desugar_bc(self, stmts, brk, cont):
        """Rewrite break -> brk=True, continue -> cont=True, and guard
        every statement that follows a potential flag set with
        `if not (brk or cont):` — the guards become lax.cond via the
        normal if conversion, so `while` bodies with break/continue
        compile into the SAME lax.while_loop (flag folded into the
        loop condition)."""

        def set_flag(name):
            return ast.Assign(targets=[_name(name, ast.Store())],
                              value=ast.Constant(value=True))

        def not_skipping():
            return ast.UnaryOp(op=ast.Not(), operand=ast.BoolOp(
                op=ast.Or(), values=[_name(brk), _name(cont)]))

        def rewrite(block):
            out = []
            for idx, s in enumerate(block):
                rest = block[idx + 1:]
                if isinstance(s, ast.Break):
                    out.append(set_flag(brk))
                    return out          # rest is unreachable
                if isinstance(s, ast.Continue):
                    out.append(set_flag(cont))
                    return out
                if isinstance(s, ast.If) and self._contains_bc(s):
                    new_if = ast.If(
                        test=s.test,
                        body=rewrite(s.body) or [ast.Pass()],
                        orelse=rewrite(s.orelse))
                    out.append(new_if)
                    if rest:
                        tail = rewrite(rest)
                        if tail:
                            out.append(ast.If(test=not_skipping(),
                                              body=tail, orelse=[]))
                    return out
                if isinstance(s, (ast.With, ast.AsyncWith, ast.Try)) \
                        and self._contains_bc(s):
                    raise _Unsupported(
                        'break/continue inside with/try in a converted '
                        'while')
                out.append(s)
            return out

        return rewrite(stmts)

    def _rewrite_while(self, node):
        if _returns_directly(node.body):
            raise _Unsupported('return in converted while')
        if node.orelse:
            raise _Unsupported('while/else')
        uid = self._uid()
        pre = []
        body_stmts = list(node.body)
        test_ast = node.test
        if _has(node.body, (ast.Break, ast.Continue)):
            brk, cont = f'__cf_brk_{uid}', f'__cf_cont_{uid}'
            body_stmts = self._desugar_bc(body_stmts, brk, cont)
            # cont resets every iteration (loop-local); brk is carried
            # and folds into the loop condition
            body_stmts = [ast.Assign(
                targets=[_name(cont, ast.Store())],
                value=ast.Constant(value=False))] + body_stmts
            pre = [ast.Assign(targets=[_name(brk, ast.Store())],
                              value=ast.Constant(value=False))]
            test_ast = ast.BoolOp(op=ast.And(), values=[
                ast.UnaryOp(op=ast.Not(), operand=_name(brk)),
                node.test])
        test = self._convert_test(test_ast)
        body = self._transform_block(body_stmts)
        stores = sorted(set(_stores(body_stmts)))
        if not stores:
            raise _Unsupported('while body assigns nothing')
        cname, bname = f'__cf_cond_{uid}', f'__cf_body_{uid}'
        stmts = [
            self._fn_def(cname, stores, [ast.Return(value=test)]),
            self._fn_def(bname, stores,
                         body + [ast.Return(value=_tuple_of(stores))]),
            ast.Assign(
                targets=[_tuple_of(stores, ast.Store())],
                value=_call(_jst('convert_while_loop'), [
                    _name(cname), _name(bname), self._grab_call(stores)])),
        ]
        return pre + stmts

    def _transform_block(self, stmts, fn_exit=False):
        out = []
        i = 0
        while i < len(stmts):
            s = stmts[i]
            if isinstance(s, ast.If):
                new, consumed = self._rewrite_if(s, stmts[i + 1:],
                                                 fn_exit)
                out.extend(new)
                if consumed:
                    return out
                i += 1
                continue
            if isinstance(s, ast.While):
                out.extend(self._rewrite_while(s))
                i += 1
                continue
            if self._is_range_for(s):
                # desugar to a counter while and convert THAT (tensor
                # range bounds lower to lax.while_loop; concrete ones
                # run as plain python inside convert_while_loop)
                out.extend(self._transform_block(
                    self._desugar_range_for(s), fn_exit=False))
                i += 1
                continue
            out.append(self.visit(s))
            i += 1
        return out


def _transform_source(fn):
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise _Unsupported('not a plain function definition')
    fdef.decorator_list = []  # avoid re-applying @to_static etc.
    tr = _ControlFlowTransformer()
    fdef.body = tr._transform_block(fdef.body, fn_exit=True)
    if tr.n == 0:
        return None  # nothing to convert
    ast.fix_missing_locations(tree)
    module_code = compile(tree, filename=f'<dy2static {fn.__qualname__}>',
                          mode='exec')
    inner = next(c for c in module_code.co_consts
                 if isinstance(c, types.CodeType)
                 and c.co_name == fdef.name)
    # bind against the LIVE module globals (not a snapshot) so later
    # global reassignments / monkeypatches stay visible; only the _JST
    # helper binding is added
    import sys
    g = fn.__globals__
    g.setdefault(_JST, sys.modules[__name__])
    new = types.FunctionType(inner, g, fn.__name__, fn.__defaults__)
    new.__kwdefaults__ = fn.__kwdefaults__
    new = functools.wraps(fn)(new)
    return new


_cache = {}


def convert_control_flow(fn):
    """AST-convert tensor control flow in `fn`; returns `fn` unchanged
    when conversion is impossible (no source, closures, unsupported
    constructs) — plain tracing then behaves exactly as before."""
    if isinstance(fn, types.MethodType):
        converted = convert_control_flow(fn.__func__)
        if converted is fn.__func__:
            return fn
        return types.MethodType(converted, fn.__self__)
    key = getattr(fn, '__code__', None)
    if key is None:
        return fn
    if key in _cache:
        return _cache[key]
    out = fn
    try:
        if not fn.__code__.co_freevars:  # closures: bail (see docstring)
            t = _transform_source(fn)
            if t is not None:
                out = t
    except (_Unsupported, OSError, TypeError, SyntaxError, ValueError):
        out = fn
    _cache[key] = out
    return out
