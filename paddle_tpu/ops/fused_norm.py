"""Fused LayerNorm (Pallas forward, stats-reusing backward).

Reference analogue: the reference's layer_norm CUDA kernel
(paddle/fluid/operators/layer_norm_op.cu); here the forward is one
Pallas pass (mean/rstd in f32, normalize+affine fused) and the backward
reuses the saved stats through XLA.  SURVEY.md §2 item 36.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _gating

__all__ = ['fused_layer_norm']

_BLOCK_ROWS = 256


def _reference(x, gamma, beta, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    return y.astype(x.dtype)


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)                      # [rows, H]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd
    y = y * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = jnp.broadcast_to(mean, mean_ref.shape)
    rstd_ref[:] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _fwd_pallas(x2d, gamma, beta, eps, block_rows):
    n, h = x2d.shape
    grid = (n // block_rows,)
    kernel = functools.partial(_fwd_kernel, eps=eps)
    y, mean, rstd = pl.pallas_call(
        kernel,
        interpret=_gating.INTERPRET,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 128), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((n, 128), jnp.float32),
            jax.ShapeDtypeStruct((n, 128), jnp.float32),
        ],
    )(x2d, gamma, beta)
    return y, mean[:, 0], rstd[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln(x2d, gamma, beta, eps, block_rows):
    y, _, _ = _fwd_pallas(x2d, gamma, beta, eps, block_rows)
    return y


def _ln_fwd(x2d, gamma, beta, eps, block_rows):
    y, mean, rstd = _fwd_pallas(x2d, gamma, beta, eps, block_rows)
    return y, (x2d, gamma, mean, rstd)


def _ln_bwd(eps, block_rows, res, g):
    x2d, gamma, mean, rstd = res
    xf = x2d.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    xhat = (xf - mean[:, None]) * rstd[:, None]
    dy = gf * gamma.astype(jnp.float32)
    h = x2d.shape[-1]
    dx = (dy - jnp.mean(dy, axis=-1, keepdims=True)
          - xhat * jnp.mean(dy * xhat, axis=-1, keepdims=True)) \
        * rstd[:, None]
    dgamma = jnp.sum(gf * xhat, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(gf, axis=0)
    return dx.astype(x2d.dtype), dgamma, dbeta.astype(gamma.dtype)


_ln.defvjp(_ln_fwd, _ln_bwd)


def fused_layer_norm(x, gamma=None, beta=None, eps=1e-5,
                     block_rows=_BLOCK_ROWS):
    """LayerNorm over the last axis; Pallas-fused on TPU."""
    h = x.shape[-1]
    lead = x.shape[:-1]
    n = 1
    for s in lead:
        n *= s
    from ._gating import pallas_backend_ok, pick_block_rows
    br = pick_block_rows(n, block_rows)
    if not (pallas_backend_ok() and gamma is not None
            and beta is not None and h % 128 == 0 and br):
        return _reference(x, gamma, beta, eps)
    y = _ln(x.reshape(n, h), gamma, beta, eps, br)
    return y.reshape(x.shape)
