"""Single source of truth for "may this op take its Pallas path?".

Single-chip: kernels run directly (`pallas_backend_ok`).  Under a mesh
the GSPMD partitioner owns most ops, but attention composes with the
mesh through an explicit shard_map (flash_attention_spmd) — gate that
with `pallas_tpu_ok`, which drops the no-mesh condition.

PADDLE_TPU_PALLAS_INTERPRET=1 runs every kernel in Pallas interpret
mode (pure Python, any backend) — correctness testing on the CPU mesh.
"""
import os

import jax

INTERPRET = os.environ.get('PADDLE_TPU_PALLAS_INTERPRET') == '1'


def pallas_tpu_ok():
    """Pallas kernels may run (mesh or not)."""
    return jax.default_backend() == 'tpu' or INTERPRET


def pallas_backend_ok():
    from ..distributed import env as _env
    return pallas_tpu_ok() and _env.get_mesh() is None


def pick_block_rows(n_rows, block_rows):
    """Largest power-of-two divisor of n_rows up to block_rows, or None
    when no usable block exists (caller falls back)."""
    br = block_rows
    while br > 1 and n_rows % br != 0:
        br //= 2
    return br if (n_rows % br == 0 and br >= 8) else None
