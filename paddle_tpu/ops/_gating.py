"""Single source of truth for "may this op take its Pallas path?".

The kernels run single-chip only for now: under a mesh the GSPMD
partitioner owns the op (shard_map + ring-attention integration is the
multi-chip upgrade), and off-TPU the jnp references run.
"""
import jax


def pallas_backend_ok():
    from ..distributed import env as _env
    return jax.default_backend() == 'tpu' and _env.get_mesh() is None


def pick_block_rows(n_rows, block_rows):
    """Largest power-of-two divisor of n_rows up to block_rows, or None
    when no usable block exists (caller falls back)."""
    br = block_rows
    while br > 1 and n_rows % br != 0:
        br //= 2
    return br if (n_rows % br == 0 and br >= 8) else None
