"""Ring attention — causal attention over sequence-sharded K/V.

Reference analogue: none in-tree (the reference caps sequence length per
GPU); the brief requires long-sequence support.  Design follows the
ring-attention recipe (Liu et al.; see PAPERS.md): each `sp` shard holds
a T/sp slice of Q/K/V, K/V blocks rotate around the ring via
`lax.ppermute` (XLA schedules the transfers over ICI so step i+1's K/V
moves while step i computes), and a streaming online-softmax merges the
per-block partials — the full [T, T] score matrix never exists and each
chip's attention memory is O((T/sp)^2).

The step body is wrapped in jax.checkpoint so the backward pass
recomputes per-block scores instead of storing every rotated K/V.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['ring_attention', 'ring_attention_spmd']

NEG_INF = -1e30


def _block_attend(q, k, v, q_chunk, k_chunk, t_local, causal):
    """Partial scores of local q against one rotated K/V block.

    q_chunk/k_chunk are ring positions of the chunks (traced scalars).
    Returns (m, l, o_unnormalized) for online-softmax merging."""
    s = jnp.einsum('bqd,bkd->bqk', q.astype(jnp.float32),
                   k.astype(jnp.float32))
    if causal:
        rows = jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 0) + q_chunk * t_local
        cols = jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 1) + k_chunk * t_local
        s = jnp.where(rows[None] >= cols[None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # fully-masked rows: exp(NEG_INF - NEG_INF) would be 1 — clamp m
    m = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum('bqk,bkd->bqd', p, v.astype(jnp.float32))
    return m, l, o


def ring_attention(q, k, v, axis_name, causal=True, scale=None,
                   use_flash=None):
    """Attention inside shard_map: q/k/v are the LOCAL [B*H, T/sp, D]
    shards; K/V rotate around `axis_name`.  Returns local output shard.

    Two per-block engines:
    - einsum (default off-TPU): O((T/sp)^2) scores per block, masked.
    - flash (`use_flash`, auto on TPU when the local shapes tile): each
      visible block runs the Pallas kernel via flash_attention_lse and
      partials merge in (out, lse) space — per-block memory drops to
      O(block) and the kernel skips masked tiles, so the diagonal block
      costs half.  Fully-masked future blocks skip compute entirely in
      BOTH engines (lax.cond/switch on the rotated chunk index).
    """
    sp = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if use_flash is None:
        # gate on pallas_tpu_ok, NOT pallas_backend_ok: ring attention
        # always runs inside a shard_map on an sp-mesh, where the
        # kernel sees only its local shard (the same r3 finding that
        # created can_use_pallas_spmd — a mesh must not veto here)
        from ._gating import pallas_tpu_ok
        from .flash_attention import _tuned_blocks
        fbq, fbk = _tuned_blocks(t_local, t_local, q.shape[-1], causal)
        fbq, fbk = min(fbq, t_local), min(fbk, t_local)
        use_flash = (pallas_tpu_ok()
                     and t_local % fbq == 0 and t_local % fbk == 0
                     and q.shape[-1] % 64 == 0
                     and fbq >= 128 and fbk >= 128)
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal, scale, sp, rank,
                           t_local)

    qs = q.astype(jnp.float32) * scale

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def merge(acc, part):
        m_acc, l_acc, o_acc = acc
        m, l, o = part
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m - m_new)
        return (m_new, l_acc * alpha + l * beta,
                o_acc * alpha + o * beta)

    def skipped(kb, vb):
        # identity partial under merge (m=NEG_INF => beta==0)
        shp = (qs.shape[0], t_local, 1)
        return (jnp.full(shp, NEG_INF, jnp.float32),
                jnp.zeros(shp, jnp.float32),
                jnp.zeros(qs.shape, jnp.float32))

    @jax.checkpoint
    def step(carry, i):
        m_acc, l_acc, o_acc, kb, vb = carry
        # rotate first (step i holds a block i hops from home); the last
        # block is consumed without a trailing, wasted ppermute
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        k_chunk = (rank - i) % sp
        if causal:
            # future chunks are fully masked — skip their FLOPs
            part = jax.lax.cond(
                k_chunk > rank, skipped,
                lambda kb, vb: _block_attend(qs, kb, vb, rank, k_chunk,
                                             t_local, causal), kb, vb)
        else:
            part = _block_attend(qs, kb, vb, rank, k_chunk, t_local,
                                 causal)
        m_acc, l_acc, o_acc = merge((m_acc, l_acc, o_acc), part)
        return (m_acc, l_acc, o_acc, kb, vb), None

    # step 0: the home block, no rotation needed
    acc = _block_attend(qs, k, v, rank, rank, t_local, causal)
    (m_acc, l_acc, o_acc, _, _), _ = jax.lax.scan(
        step, acc + (k, v), jnp.arange(1, sp))
    out = o_acc / jnp.maximum(l_acc, 1e-30)
    return out.astype(q.dtype)


def _ring_flash(q, k, v, axis_name, causal, scale, sp, rank, t_local):
    """Flash-blocked ring: every visible block is one Pallas kernel
    call; partials merge in (out, lse) space.  The lse gradient is
    exact through flash_attention_lse's custom vjp."""
    from .flash_attention import flash_attention_lse, _tuned_blocks
    bq, bk = _tuned_blocks(t_local, t_local, q.shape[-1], causal)
    bq, bk = min(bq, t_local), min(bk, t_local)
    f32 = jnp.float32

    def full_blk(kb, vb):
        o, l = flash_attention_lse(q, kb, vb, False, scale, bq, bk)
        return o.astype(f32), l

    def diag_blk(kb, vb):
        o, l = flash_attention_lse(q, kb, vb, True, scale, bq, bk)
        return o.astype(f32), l

    def skip_blk(kb, vb):
        return (jnp.zeros(q.shape, f32),
                jnp.full(q.shape[:2], -jnp.inf, f32))

    def merge(acc, part):
        o_a, l_a = acc
        o_b, l_b = part
        l_n = jnp.logaddexp(l_a, l_b)
        # l_a is finite after the home block, so no -inf - -inf NaN
        return (o_a * jnp.exp(l_a - l_n)[..., None]
                + o_b * jnp.exp(l_b - l_n)[..., None], l_n)

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    @jax.checkpoint
    def step(carry, i):
        o_acc, l_acc, kb, vb = carry
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        k_chunk = (rank - i) % sp
        if causal:
            part = jax.lax.cond(k_chunk > rank, skip_blk, full_blk,
                                kb, vb)
        else:
            part = full_blk(kb, vb)
        o_acc, l_acc = merge((o_acc, l_acc), part)
        return (o_acc, l_acc, kb, vb), None

    o0, l0 = diag_blk(k, v) if causal else full_blk(k, v)
    (o_acc, l_acc, _, _), _ = jax.lax.scan(
        step, (o0, l0, k, v), jnp.arange(1, sp))
    return o_acc.astype(q.dtype)


def ring_attention_spmd(q, k, v, mesh, causal=True,
                        batch_axes=('dp', 'tp'), seq_axis='sp',
                        use_flash=None):
    """shard_map wrapper: q/k/v are GLOBAL [B*H, T, D] arrays (traced
    under jit on `mesh`); heads/batch split over `batch_axes`, sequence
    over `seq_axis`; ring rotation rides the `sp` ICI ring."""
    axes = tuple(a for a in batch_axes if a in mesh.shape)
    spec = P(axes if len(axes) > 1 else (axes[0] if axes else None),
             seq_axis, None)
    fn = functools.partial(ring_attention, axis_name=seq_axis,
                           causal=causal, use_flash=use_flash)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
