"""Ring attention — causal attention over sequence-sharded K/V.

Reference analogue: none in-tree (the reference caps sequence length per
GPU); the brief requires long-sequence support.  Design follows the
ring-attention recipe (Liu et al.; see PAPERS.md): each `sp` shard holds
a T/sp slice of Q/K/V, K/V blocks rotate around the ring via
`lax.ppermute` (XLA schedules the transfers over ICI so step i+1's K/V
moves while step i computes), and a streaming online-softmax merges the
per-block partials — the full [T, T] score matrix never exists and each
chip's attention memory is O((T/sp)^2).

The step body is wrapped in jax.checkpoint so the backward pass
recomputes per-block scores instead of storing every rotated K/V.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['ring_attention', 'ring_attention_spmd']

NEG_INF = -1e30


def _block_attend(q, k, v, q_chunk, k_chunk, t_local, causal):
    """Partial scores of local q against one rotated K/V block.

    q_chunk/k_chunk are ring positions of the chunks (traced scalars).
    Returns (m, l, o_unnormalized) for online-softmax merging."""
    s = jnp.einsum('bqd,bkd->bqk', q.astype(jnp.float32),
                   k.astype(jnp.float32))
    if causal:
        rows = jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 0) + q_chunk * t_local
        cols = jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 1) + k_chunk * t_local
        s = jnp.where(rows[None] >= cols[None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # fully-masked rows: exp(NEG_INF - NEG_INF) would be 1 — clamp m
    m = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum('bqk,bkd->bqd', p, v.astype(jnp.float32))
    return m, l, o


def ring_attention(q, k, v, axis_name, causal=True, scale=None):
    """Attention inside shard_map: q/k/v are the LOCAL [B*H, T/sp, D]
    shards; K/V rotate around `axis_name`.  Returns local output shard.
    """
    sp = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qs = q.astype(jnp.float32) * scale

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def merge(acc, part):
        m_acc, l_acc, o_acc = acc
        m, l, o = part
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m - m_new)
        return (m_new, l_acc * alpha + l * beta,
                o_acc * alpha + o * beta)

    @jax.checkpoint
    def step(carry, i):
        m_acc, l_acc, o_acc, kb, vb = carry
        # rotate first (step i holds a block i hops from home); the last
        # block is consumed without a trailing, wasted ppermute
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        k_chunk = (rank - i) % sp
        part = _block_attend(qs, kb, vb, rank, k_chunk, t_local, causal)
        m_acc, l_acc, o_acc = merge((m_acc, l_acc, o_acc), part)
        return (m_acc, l_acc, o_acc, kb, vb), None

    # step 0: the home block, no rotation needed
    acc = _block_attend(qs, k, v, rank, rank, t_local, causal)
    (m_acc, l_acc, o_acc, _, _), _ = jax.lax.scan(
        step, acc + (k, v), jnp.arange(1, sp))
    out = o_acc / jnp.maximum(l_acc, 1e-30)
    return out.astype(q.dtype)


def ring_attention_spmd(q, k, v, mesh, causal=True,
                        batch_axes=('dp', 'tp'), seq_axis='sp'):
    """shard_map wrapper: q/k/v are GLOBAL [B*H, T, D] arrays (traced
    under jit on `mesh`); heads/batch split over `batch_axes`, sequence
    over `seq_axis`; ring rotation rides the `sp` ICI ring."""
    axes = tuple(a for a in batch_axes if a in mesh.shape)
    spec = P(axes if len(axes) > 1 else (axes[0] if axes else None),
             seq_axis, None)
    fn = functools.partial(ring_attention, axis_name=seq_axis,
                           causal=causal)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
