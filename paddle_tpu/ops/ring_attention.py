"""Ring attention — causal attention over sequence-sharded K/V.

Reference analogue: none in-tree (the reference caps sequence length per
GPU); the brief requires long-sequence support.  Design follows the
ring-attention recipe (Liu et al.; see PAPERS.md): each `sp` shard holds
a T/sp slice of Q/K/V, K/V blocks rotate around the ring via
`lax.ppermute` (XLA schedules the transfers over ICI so step i+1's K/V
moves while step i computes), and a streaming online-softmax merges the
per-block partials — the full [T, T] score matrix never exists and each
chip's attention memory is O((T/sp)^2).

The step body is wrapped in jax.checkpoint so the backward pass
recomputes per-block scores instead of storing every rotated K/V.
"""
import functools
import math

import jax
from ..core.jaxcompat import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['ring_attention', 'ring_attention_spmd', 'stripe_tokens',
           'unstripe_tokens', 'ring_attention_striped']

NEG_INF = -1e30


def _flash_gate_and_blocks(t_local, d, causal):
    """(ok, bq, bk): may the per-block engine take the Pallas kernel?
    Gates on pallas_tpu_ok, NOT pallas_backend_ok: the ring always runs
    inside a shard_map on an sp-mesh, where the kernel only ever sees
    its local shard (same r3 finding that created
    can_use_pallas_spmd — an installed mesh must not veto)."""
    from ._gating import pallas_tpu_ok
    from .flash_attention import _tuned_blocks, shapes_tile
    bq, bk = _tuned_blocks(t_local, t_local, d, causal)
    bq, bk = min(bq, t_local), min(bk, t_local)
    ok = pallas_tpu_ok() and shapes_tile(t_local, t_local, d, bq, bk)
    return ok, bq, bk


def _merge_lse(acc, part):
    """Streaming merge of (out, lse) partials; the accumulator's lse is
    finite after the home block, so a skipped partial's -inf is safe."""
    o_a, l_a = acc
    o_b, l_b = part
    l_n = jnp.logaddexp(l_a, l_b)
    return (o_a * jnp.exp(l_a - l_n)[..., None]
            + o_b * jnp.exp(l_b - l_n)[..., None], l_n)


def _block_attend(q, k, v, q_chunk, k_chunk, t_local, causal, scale):
    """Partial scores of local q against one rotated K/V block.

    q_chunk/k_chunk are ring positions of the chunks (traced scalars).
    Returns (m, l, o_unnormalized) for online-softmax merging.

    q/k stay in their storage dtype with an f32 MXU accumulator
    (preferred_element_type) and the scale lands on the f32 scores —
    exactly the flash kernel's ordering.  The old operand upcast
    (q.astype(f32) @ k.astype(f32)) forced the ~8x-slower f32 MXU
    path and doubled the rotated blocks' read bytes (tpu-lint
    amp-promotion)."""
    s = jnp.einsum('bqd,bkd->bqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 0) + q_chunk * t_local
        cols = jax.lax.broadcasted_iota(
            jnp.int32, s.shape[-2:], 1) + k_chunk * t_local
        s = jnp.where(rows[None] >= cols[None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # fully-masked rows: exp(NEG_INF - NEG_INF) would be 1 — clamp m
    m = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    # p is genuinely f32 (softmax weights): the mixed-precision dot
    # accumulates in f32 without re-reading v as f32 from HBM
    o = jnp.einsum('bqk,bkd->bqd', p, v,
                   preferred_element_type=jnp.float32)
    return m, l, o


def ring_attention(q, k, v, axis_name, causal=True, scale=None,
                   use_flash=None):
    """Attention inside shard_map: q/k/v are the LOCAL [B*H, T/sp, D]
    shards; K/V rotate around `axis_name`.  Returns local output shard.

    Two per-block engines:
    - einsum (default off-TPU): O((T/sp)^2) scores per block, masked.
    - flash (`use_flash`, auto on TPU when the local shapes tile): each
      visible block runs the Pallas kernel via flash_attention_lse and
      partials merge in (out, lse) space — per-block memory drops to
      O(block) and the kernel skips masked tiles, so the diagonal block
      costs half.  Fully-masked future blocks skip compute entirely in
      BOTH engines (lax.cond/switch on the rotated chunk index).
    """
    sp = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    gate_ok, fbq, fbk = _flash_gate_and_blocks(t_local, q.shape[-1],
                                               causal)
    if use_flash is None:
        use_flash = gate_ok
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal, scale, sp, rank,
                           t_local, fbq, fbk)

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def merge(acc, part):
        m_acc, l_acc, o_acc = acc
        m, l, o = part
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m - m_new)
        return (m_new, l_acc * alpha + l * beta,
                o_acc * alpha + o * beta)

    def skipped(kb, vb):
        # identity partial under merge (m=NEG_INF => beta==0)
        shp = (q.shape[0], t_local, 1)
        return (jnp.full(shp, NEG_INF, jnp.float32),
                jnp.zeros(shp, jnp.float32),
                jnp.zeros(q.shape, jnp.float32))

    @jax.checkpoint
    def step(carry, i):
        m_acc, l_acc, o_acc, kb, vb = carry
        # rotate first (step i holds a block i hops from home); the last
        # block is consumed without a trailing, wasted ppermute
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        k_chunk = (rank - i) % sp
        if causal:
            # future chunks are fully masked — skip their FLOPs
            part = jax.lax.cond(
                k_chunk > rank, skipped,
                lambda kb, vb: _block_attend(q, kb, vb, rank, k_chunk,
                                             t_local, causal, scale),
                kb, vb)
        else:
            part = _block_attend(q, kb, vb, rank, k_chunk, t_local,
                                 causal, scale)
        m_acc, l_acc, o_acc = merge((m_acc, l_acc, o_acc), part)
        return (m_acc, l_acc, o_acc, kb, vb), None

    # step 0: the home block, no rotation needed
    acc = _block_attend(q, k, v, rank, rank, t_local, causal, scale)
    (m_acc, l_acc, o_acc, _, _), _ = jax.lax.scan(
        step, acc + (k, v), jnp.arange(1, sp))
    out = o_acc / jnp.maximum(l_acc, 1e-30)
    return out.astype(q.dtype)


def _ring_flash(q, k, v, axis_name, causal, scale, sp, rank, t_local,
                bq, bk):
    """Flash-blocked ring: every visible block is one Pallas kernel
    call; partials merge in (out, lse) space.  The lse gradient is
    exact through flash_attention_lse's custom vjp."""
    from .flash_attention import flash_attention_lse
    f32 = jnp.float32

    def full_blk(kb, vb):
        o, l = flash_attention_lse(q, kb, vb, False, scale, bq, bk)
        return o.astype(f32), l

    def diag_blk(kb, vb):
        o, l = flash_attention_lse(q, kb, vb, True, scale, bq, bk)
        return o.astype(f32), l

    def skip_blk(kb, vb):
        return (jnp.zeros(q.shape, f32),
                jnp.full(q.shape[:2], -jnp.inf, f32))

    merge = _merge_lse
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    @jax.checkpoint
    def step(carry, i):
        o_acc, l_acc, kb, vb = carry
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        k_chunk = (rank - i) % sp
        if causal:
            part = jax.lax.cond(k_chunk > rank, skip_blk, full_blk,
                                kb, vb)
        else:
            part = full_blk(kb, vb)
        o_acc, l_acc = merge((o_acc, l_acc), part)
        return (o_acc, l_acc, kb, vb), None

    o0, l0 = diag_blk(k, v) if causal else full_blk(k, v)
    (o_acc, l_acc, _, _), _ = jax.lax.scan(
        step, (o0, l0, k, v), jnp.arange(1, sp))
    return o_acc.astype(q.dtype)


def stripe_tokens(x, sp, axis=1):
    """Natural -> striped token order: token t = i*sp + s moves to
    position s*(T/sp) + i, so a contiguous shard s over `axis` holds
    the STRIDED tokens {s, s+sp, s+2sp, ...}.  Apply once at the model
    boundary (ids in, logits/labels out) — attention is the only
    position-coupled op, so the hidden states can live striped."""
    T = x.shape[axis]
    t_local = T // sp
    shape = list(x.shape)
    x = jnp.moveaxis(x, axis, 0)
    x = x.reshape((t_local, sp) + x.shape[1:])
    x = jnp.swapaxes(x, 0, 1).reshape((T,) + x.shape[2:])
    return jnp.moveaxis(x, 0, axis).reshape(shape)


def unstripe_tokens(x, sp, axis=1):
    """Inverse of stripe_tokens."""
    T = x.shape[axis]
    t_local = T // sp
    shape = list(x.shape)
    x = jnp.moveaxis(x, axis, 0)
    x = x.reshape((sp, t_local) + x.shape[1:])
    x = jnp.swapaxes(x, 0, 1).reshape((T,) + x.shape[2:])
    return jnp.moveaxis(x, 0, axis).reshape(shape)


def ring_attention_striped(q, k, v, axis_name, scale=None,
                           use_flash=None):
    """Load-BALANCED causal ring over STRIPED token layout
    (Striped Attention, Brandon et al. 2023; see PAPERS.md pattern
    notes): device s holds tokens {s, s+sp, ...} (stripe_tokens), so
    global causality token i*sp+r >= j*sp+s reduces per block-pair to
    plain causal (i >= j) when r >= s and STRICT causal (i > j) when
    r < s.  Every device computes a ~half-masked block at EVERY ring
    step — wall-clock ~sp * block/2 versus the contiguous ring's
    sp * block (where whichever device holds a fully-visible pair sets
    the pace).  Inputs/outputs are local striped shards inside
    shard_map, like ring_attention."""
    sp = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    gate_ok, bq, bk = _flash_gate_and_blocks(t_local, q.shape[-1],
                                             True)
    if use_flash is None:
        use_flash = gate_ok
    f32 = jnp.float32

    if use_flash:
        from .flash_attention import flash_attention_lse

        def attend(kb, vb, mode):
            o, l = flash_attention_lse(q, kb, vb, mode, scale, bq, bk)
            return o.astype(f32), l
    else:
        from .flash_attention import _reference_lse

        def attend(kb, vb, mode):
            # shares the masked-softmax-with-lse math (incl. the
            # fully-masked-row guards) with the flash fallback
            return _reference_lse(q, kb, vb, mode, scale)

    merge = _merge_lse
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    @jax.checkpoint
    def step(carry, i):
        o_acc, l_acc, kb, vb = carry
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        s = (rank - i) % sp
        # rank >= s: diagonal included; rank < s: strictly causal
        part = jax.lax.cond(rank >= s,
                            lambda kb, vb: attend(kb, vb, True),
                            lambda kb, vb: attend(kb, vb, 'strict'),
                            kb, vb)
        o_acc, l_acc = merge((o_acc, l_acc), part)
        return (o_acc, l_acc, kb, vb), None

    o0, l0 = attend(k, v, True)           # home block: r == s
    (o_acc, l_acc, _, _), _ = jax.lax.scan(
        step, (o0, l0, k, v), jnp.arange(1, sp))
    return o_acc.astype(q.dtype)


def ring_attention_spmd(q, k, v, mesh, causal=True,
                        batch_axes=('dp', 'tp'), seq_axis='sp',
                        use_flash=None, striped=False,
                        pre_striped=False):
    """shard_map wrapper: q/k/v are GLOBAL [B*H, T, D] arrays (traced
    under jit on `mesh`); heads/batch split over `batch_axes`, sequence
    over `seq_axis`; ring rotation rides the `sp` ICI ring.

    `striped=True` (causal only) runs the load-balanced striped ring:
    inputs are striped/unstriped here for drop-in numerics — GSPMD
    inserts the relayout all-to-alls, so pipelines chasing the full 2x
    keep hidden states striped end-to-end and pass `pre_striped=True`
    (inputs already in stripe order; output stays striped)."""
    axes = tuple(a for a in batch_axes if a in mesh.shape)
    spec = P(axes if len(axes) > 1 else (axes[0] if axes else None),
             seq_axis, None)
    if striped and not causal:
        raise ValueError(
            'striped=True requires causal=True: the stripe layout '
            'exists to balance the causal mask; non-causal rings are '
            'already balanced — drop striped.')
    if striped:
        sp = mesh.shape[seq_axis]
        fn = functools.partial(ring_attention_striped,
                               axis_name=seq_axis, use_flash=use_flash)
        if not pre_striped:
            q, k, v = (stripe_tokens(t, sp) for t in (q, k, v))
        out = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False)(q, k, v)
        return out if pre_striped else unstripe_tokens(out, sp)
    fn = functools.partial(ring_attention, axis_name=seq_axis,
                           causal=causal, use_flash=use_flash)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
