"""FlashAttention for TPU (Pallas).

Reference analogue: the reference's fused attention goes through cuDNN
(paddle/fluid/operators/fused/fmha*); this is the TPU-native equivalent:
an online-softmax tiled kernel that never materialises the [T, T] score
matrix, with a recompute-style Pallas backward (dq / dkv kernels) using
the forward's logsumexp.  SURVEY.md §2 item 36.

Layout: [B*H, T, D] (callers fold batch and heads).  f32 accumulation
regardless of input dtype (bf16 inputs hit the MXU natively).

On non-TPU backends `flash_attention` falls back to a jnp reference
implementation (same math, materialised scores) so tests/CPU runs work.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _gating

__all__ = ['flash_attention', 'flash_attention_lse', 'can_use_pallas',
           'autotune_blocks']

# tuned on v5e at T=4096 D=128: (256, 512) beats XLA's fused einsum
# attention by ~21%; see bench history
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30

# -- per-shape block tuning (PERF.md round-3 lead 4) -------------------------
# key "tq,tk,d,causal" -> [bq, bk]; populated by tools/tune_flash.py on
# the real chip and persisted next to this module, so tuned choices
# survive across processes.  Explicit block_q/block_k args always win.
_TUNE_FILE = __file__.rsplit('.', 1)[0] + '_tuning.json'
_tune_table = None


def _load_tune_table():
    global _tune_table
    if _tune_table is None:
        import json
        import os
        _tune_table = {}
        if os.path.exists(_TUNE_FILE):
            try:
                with open(_TUNE_FILE) as f:
                    _tune_table = {k: tuple(v)
                                   for k, v in json.load(f).items()}
            except (ValueError, OSError):
                _tune_table = {}
    return _tune_table


def _tuned_blocks(tq, tk, d, causal):
    table = _load_tune_table()
    got = table.get(f'{tq},{tk},{d},{int(bool(causal))}')
    return got if got else (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)


def autotune_blocks(tq, tk, d, causal=True, dtype=jnp.bfloat16,
                    bh=8, candidates=None, iters=8, persist=True):
    """Time the kernel per (bq, bk) candidate ON THE LIVE DEVICE and
    record the winner in the tuning table (the cuDNN-style heuristic
    table the reference gets from NVIDIA, built empirically here).
    Returns ((bq, bk), ms)."""
    import time
    import numpy as np

    cands = candidates or [(bq, bk)
                           for bq in (128, 256, 512)
                           for bk in (128, 256, 512, 1024)]
    cands = [(bq, bk) for bq, bk in cands
             if tq % min(bq, tq) == 0 and tk % min(bk, tk) == 0
             and can_use_pallas(tq, tk, d, bq, bk)]
    if not cands:
        return (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K), float('nan')
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(bh, tq, d), dtype)
    k = jnp.asarray(rs.randn(bh, tk, d), dtype)
    v = jnp.asarray(rs.randn(bh, tk, d), dtype)
    scale = 1.0 / math.sqrt(d)
    best, best_ms = None, float('inf')
    for bq, bk in cands:
        bq_, bk_ = min(bq, tq), min(bk, tk)

        # amortize dispatch: chain the kernel in-graph (PERF.md
        # methodology — single calls through the tunnel mis-time)
        @jax.jit
        def run(q, k, v, bq_=bq_, bk_=bk_):
            # chain on Q (output shape == Q shape) so the scan carries
            # a real data dependency between kernel invocations
            def body(c, _):
                return _flash(c, k, v, causal, scale, bq_, bk_), None
            out, _ = jax.lax.scan(body, q, None, length=iters)
            return out

        try:
            float(np.asarray(run(q, k, v)).ravel()[0])   # compile+warm
            t0 = time.perf_counter()
            float(np.asarray(run(q, k, v)).ravel()[0])
            ms = (time.perf_counter() - t0) * 1000 / iters
        except Exception:
            continue
        if ms < best_ms:
            best, best_ms = (bq_, bk_), ms
    if best is None:
        return (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K), float('nan')
    table = _load_tune_table()
    table[f'{tq},{tk},{d},{int(bool(causal))}'] = best
    if persist:
        import json
        try:
            with open(_TUNE_FILE, 'w') as f:
                json.dump({k: list(v) for k, v in table.items()}, f,
                          indent=1)
        except OSError:
            pass
    return best, best_ms


def _reference_lse(q, k, v, causal, scale):
    s = jnp.einsum('bqd,bkd->bqk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool),
                        k=-1 if causal == 'strict' else 0)
        s = jnp.where(mask, s, NEG_INF)
    # masked-softmax that zeroes fully-masked rows (strict mode's row
    # 0) instead of going uniform — matches the Pallas kernels
    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), -1e29)
    p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m))
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum('bqk,bkd->bqd', p, v.astype(jnp.float32)) \
        / jnp.maximum(l, 1e-30)
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    return o, lse


def _reference(q, k, v, causal, scale):
    o, _ = _reference_lse(q, k, v, causal, scale)
    return o.astype(q.dtype)


# -- forward kernel ----------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_sc, m_sc, l_sc, *, scale, causal, block_q, block_k,
                num_k_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    def compute():
        q = q_ref[0].astype(jnp.float32)                 # [bq, d]
        kb = k_ref[0].astype(jnp.float32)                # [bk, d]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + qi * block_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + ki * block_k
            s = jnp.where(rows > cols if causal == 'strict'
                          else rows >= cols, s, NEG_INF)
        m_prev = m_sc[:, :1]                              # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                            # [bq, bk]
        if causal == 'strict':
            # a fully-masked row (global token 0) has m_new == NEG_INF,
            # making exp(s - m_new) == 1 on masked cells — zero them
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)                   # [bq, 1]
        l_new = alpha * l_sc[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[:] = acc_sc[:] * alpha + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    if causal:
        # skip blocks strictly above the diagonal
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_sc[:, :1]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_sc[:] / safe_l).astype(o_ref.dtype)
        lse = (m_sc[:, :1] + jnp.log(safe_l)).astype(jnp.float32)
        # (block_q, 8): narrowest legal tile for per-row scalars
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd_pallas(q, k, v, scale, causal, block_q, block_k):
    bh, tq, d = q.shape
    tk = k.shape[1]
    grid = (bh, tq // block_q, tk // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=tk // block_k)
    out, lse = pl.pallas_call(
        kernel,
        interpret=_gating.INTERPRET,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
    )(q, k, v)
    return out, lse


# -- backward kernels --------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_sc, *, scale, causal, block_q, block_k,
                   num_k_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]                           # [bq, 1]
        delta = delta_ref[0][:, :1]                       # [bq, 1]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + qi * block_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + ki * block_k
            s = jnp.where(rows > cols if causal == 'strict'
                          else rows >= cols, s, NEG_INF)
        p = jnp.exp(jnp.minimum(s - lse, 0.0))            # [bq, bk]
        if causal == 'strict':
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        ds = p * (dp - delta) * scale
        dq_sc[:] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_sc, dv_sc, *, scale, causal,
                    block_q, block_k, num_q_blocks):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + qi * block_q
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + ki * block_k
            s = jnp.where(rows > cols if causal == 'strict'
                          else rows >= cols, s, NEG_INF)
        p = jnp.exp(jnp.minimum(s - lse, 0.0))            # [bq, bk]
        if causal == 'strict':
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        dv_sc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, d]
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        ds = p * (dp - delta) * scale
        dk_sc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, d]

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_pallas(res, g, scale, causal, block_q, block_k, g_lse=None):
    q, k, v, out, lse = res
    bh, tq, d = q.shape
    tk = k.shape[1]
    do = g
    # delta_i = rowsum(dO_i * O_i) — f32, broadcast into lane dim 128
    # per-row scalars ride a (bh, tq, 8) layout — the narrowest tile the
    # TPU lowering accepts (vs 128 lanes: 16x less HBM traffic)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    if g_lse is not None:
        # lse cotangent (streaming-merge callers): dlse/ds = p, so the
        # contribution p*g_lse folds into ds = p*(dp - delta) exactly
        # as delta' = delta - g_lse — the kernels stay unchanged
        delta = delta - g_lse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[:, :, None], (bh, tq, 8))

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=tk // block_k)
    dq = pl.pallas_call(
        dq_kernel,
        interpret=_gating.INTERPRET,
        grid=(bh, tq // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )(q, k, v, do, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_q_blocks=tq // block_q)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        interpret=_gating.INTERPRET,
        grid=(bh, tk // block_k, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, ki, qi: (b, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# -- public op ---------------------------------------------------------------

def shapes_tile(tq, tk, d, block_q, block_k):
    """The single shape predicate every Pallas-attention gate shares.
    d=64 compiles fine (Mosaic pads the lane dim); smaller head dims
    waste too much of the tile."""
    bq, bk = min(block_q, tq), min(block_k, tk)
    return (tq % bq == 0 and tk % bk == 0 and d % 64 == 0
            and bq >= 128 and bk >= 128)


def can_use_pallas(tq, tk, d, block_q=DEFAULT_BLOCK_Q,
                   block_k=DEFAULT_BLOCK_K):
    """True iff flash_attention will take the Pallas path for these
    shapes — callers (e.g. GPT attention) use this to choose between
    flash and their own einsum path instead of hitting the slower jnp
    reference fallback."""
    from ._gating import pallas_backend_ok
    return pallas_backend_ok() and shapes_tile(tq, tk, d, block_q,
                                               block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _fwd_pallas(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, res, g):
    return _bwd_pallas(res, g, scale, causal, block_q, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, causal, scale, block_q, block_k):
    out, lse8 = _fwd_pallas(q, k, v, scale, causal, block_q, block_k)
    return out, lse8[:, :, 0]


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse8 = _fwd_pallas(q, k, v, scale, causal, block_q, block_k)
    return (out, lse8[:, :, 0]), (q, k, v, out, lse8)


def _flash_lse_bwd(causal, scale, block_q, block_k, res, g):
    g_out, g_lse = g
    return _bwd_pallas(res, g_out, scale, causal, block_q, block_k,
                       g_lse=g_lse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(q, k, v, causal, scale, block_q, block_k):
    """Attention returning (out, lse[bh, tq]) for streaming-merge
    callers (ring attention combines per-block partials in (out, lse)
    space).  The lse cotangent is exact: it folds into the shared
    backward kernels as delta' = delta - g_lse (_bwd_pallas), since
    d lse / d s = softmax(s).  Falls back to the jnp reference when
    Pallas is unavailable or the shapes don't tile, like
    flash_attention."""
    from ._gating import pallas_tpu_ok
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    if pallas_tpu_ok() and shapes_tile(q.shape[1], k.shape[1],
                                       q.shape[2], bq, bk):
        return _flash_lse(q, k, v, causal, scale, bq, bk)
    o, lse = _reference_lse(q, k, v, causal, scale)
    return o.astype(q.dtype), lse


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=None, block_k=None):
    """Tiled attention over [B*H, T, D] arrays.

    Uses the Pallas kernel on TPU when the sequence lengths divide the
    (>=128) block sizes and D % 64 == 0 (see can_use_pallas); otherwise
    falls back to the jnp reference (identical math, differentiable
    through XLA).  Block sizes resolve per shape from the autotune
    table (tools/tune_flash.py) unless given explicitly."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if block_q is None or block_k is None:
        tbq, tbk = _tuned_blocks(q.shape[1], k.shape[1], q.shape[2],
                                 causal)
        block_q = block_q or tbq
        block_k = block_k or tbk
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    if not can_use_pallas(q.shape[1], k.shape[1], q.shape[2], bq, bk):
        return _reference(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, bq, bk)


def flash_attention_spmd(q, k, v, mesh, causal=False, scale=None,
                         dp_axis='dp', tp_axis='tp'):
    """Flash attention COMPOSED WITH THE MESH: q/k/v are [B, H, T, D]
    global (GSPMD-traced) arrays; batch shards over dp, heads over tp,
    and each shard runs the Pallas kernel on its local [B/dp * H/tp,
    T, D] slab — attention is head-independent, so no collectives.

    This closes the "single-chip only" gating of round 2: the einsum
    attention XLA partitions automatically, but the flash kernel needs
    this explicit shard_map to ride a hybrid mesh.
    """
    from jax.sharding import PartitionSpec as P
    from ..core.jaxcompat import shard_map

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    shape = dict(mesh.shape)
    dp = shape.get(dp_axis, 1)
    tp = shape.get(tp_axis, 1)
    spec = P(dp_axis if dp > 1 else None, tp_axis if tp > 1 else None,
             None, None)

    # resolve blocks from the tuning table against the GLOBAL T (the
    # per-shard T is the same — only batch/heads shard)
    T_, D_ = q.shape[2], q.shape[3]
    bq, bk = _tuned_blocks(T_, k.shape[2], D_, causal)
    bq, bk = min(bq, T_), min(bk, k.shape[2])

    def local(qv, kv, vv):
        B, H, T, D = qv.shape
        # call the KERNEL directly: the caller already gated via
        # can_use_pallas_spmd, and flash_attention's own gate would see
        # the installed global mesh and silently fall back to the slow
        # reference inside every shard (r3 review finding)
        o = _flash(qv.reshape(B * H, T, D),
                   kv.reshape(B * H, kv.shape[2], D),
                   vv.reshape(B * H, vv.shape[2], D),
                   causal, scale, bq, bk)
        return o.reshape(B, H, T, D)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def can_use_pallas_spmd(B, H, T, d, mesh, dp_axis='dp', tp_axis='tp'):
    """Gate for flash_attention_spmd: pallas available (mesh allowed),
    batch/heads divide the mesh axes, and the LOCAL shapes tile."""
    from ._gating import pallas_tpu_ok
    if mesh is None or not pallas_tpu_ok():
        return False
    shape = dict(mesh.shape)
    dp = shape.get(dp_axis, 1)
    tp = shape.get(tp_axis, 1)
    # other model-parallel axes must not shard attention inputs
    if shape.get('sp', 1) > 1 or shape.get('pp', 1) > 1:
        return False
    if B % dp or H % tp:
        return False
    return shapes_tile(T, T, d, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
