"""Pallas TPU kernels (reference analogue: the reference's fused CUDA
ops under paddle/fluid/operators/fused/).  Each op auto-falls back to a
jnp reference implementation off-TPU or for unsupported shapes."""
from .flash_attention import flash_attention  # noqa: F401
from .fused_norm import fused_layer_norm  # noqa: F401
from .fused_softmax import fused_softmax  # noqa: F401
from .fused_gelu_linear import fused_linear_gelu  # noqa: F401

__all__ = ['flash_attention', 'fused_layer_norm', 'fused_softmax',
           'fused_linear_gelu']
