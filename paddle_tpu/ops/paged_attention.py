"""Ragged paged attention — the serving-decode kernel (RPA-style).

Reference: "Ragged Paged Attention: A High-Performance and Flexible
LLM Inference Kernel for TPU" (PAPERS.md, arxiv 2604.15464).  The
serving KV cache lives in FIXED-SIZE blocks inside one preallocated
pool (``serving/kv_cache.py``); each sequence owns a *block table* —
a row of pool indices — and a ragged length.  One decode step then
attends a whole batch of wildly different-length sequences at once:
gather each sequence's blocks through its table, mask columns past
its length, softmax, weight.

Numerics contract (pinned by test): **bit-exact vs the dense cached
attention** in ``models/gpt.py`` on the same keys/values.  Masked
columns score ``-1e9`` exactly as the dense path does, so after the
softmax's max-subtraction they underflow to exact ``0.0`` and the
extra (block-padded) lanes contribute exact zeros to every reduction
— the same argument that made PR 7's pow2 prompt bucketing bit-exact.
Valid columns occupy the same leading positions in the same order as
the dense buffer, so reduction trees agree on the real lanes.

This file is the portable jnp reference implementation (gathers
materialize [S, max_blocks*block_size] keys per layer).  On real TPU
the gather stays in HBM-friendly shape; a Pallas RPA kernel that
streams blocks without materializing the gather is the planned drop-in
(see ops/flash_attention.py for the kernel-vs-reference layering this
module will follow).
"""
import math

__all__ = ['write_kv', 'paged_attention', 'gather_dense', 'POOL_SPEC']

# sharding of one layer's pool [num_blocks, num_heads, block_size,
# head_dim]: heads ride the tp axis (same Megatron head split as the
# attention weights), blocks/positions replicated
POOL_SPEC = (None, 'tp', None, None)


def write_kv(k_pool, v_pool, k_new, v_new, block_tables, slots):
    """Scatter one new token's k/v per sequence into the paged pool.

    k_pool/v_pool : [num_blocks, num_heads, block_size, head_dim]
    k_new/v_new   : [S, num_heads, head_dim] — this step's k/v rows
    block_tables  : [S, max_blocks] int — pool indices per sequence
    slots         : [S] int — the ABSOLUTE position being written
                    (= the sequence's context length before this token)

    Returns the updated (k_pool, v_pool).  Rows whose table entry is
    the reserved trash block (0) land there harmlessly — that is how
    inactive batch slots stay in the compiled step without corrupting
    live sequences.
    """
    import jax.numpy as jnp
    bs = k_pool.shape[2]
    idx = (slots // bs).astype(jnp.int32)
    bids = jnp.take_along_axis(block_tables, idx[:, None], axis=1)[:, 0]
    offs = (slots % bs).astype(jnp.int32)
    k_pool = k_pool.at[bids, :, offs].set(k_new.astype(k_pool.dtype))
    v_pool = v_pool.at[bids, :, offs].set(v_new.astype(v_pool.dtype))
    return k_pool, v_pool


def gather_dense(pool, block_table):
    """One sequence-major dense view of the pooled cache:
    [num_blocks, nh, bs, hd] gathered through [S, max_blocks] tables
    -> [S, nh, max_blocks*bs, hd] (position-contiguous per sequence).
    """
    import jax.numpy as jnp
    S, mb = block_table.shape
    _, nh, bs, hd = pool.shape
    g = pool[block_table]                      # [S, mb, nh, bs, hd]
    g = jnp.transpose(g, (0, 2, 1, 3, 4))      # [S, nh, mb, bs, hd]
    return g.reshape(S, nh, mb * bs, hd)


def paged_attention(q, k_pool, v_pool, block_tables, lens):
    """One ragged decode step of attention over the paged cache.

    q            : [S, num_heads, head_dim] — ONE query token per
                   sequence (the continuous-batching decode shape)
    k_pool/v_pool: [num_blocks, num_heads, block_size, head_dim]
    block_tables : [S, max_blocks] int
    lens         : [S] int — valid context length per sequence,
                   INCLUDING the token just written via ``write_kv``

    -> [S, num_heads, head_dim].

    Mirrors the dense cached path in models/gpt.py operation for
    operation (same 1/sqrt(hd) scale, same -1e9 mask fill, same
    softmax) so the two are bit-exact on shared prefixes.
    """
    import jax
    import jax.numpy as jnp
    hd = q.shape[-1]
    k = gather_dense(k_pool, block_tables)     # [S, nh, mb*bs, hd]
    v = gather_dense(v_pool, block_tables)
    scores = jnp.einsum('shd,shkd->shk', q, k) * (1.0 / math.sqrt(hd))
    cols = jnp.arange(k.shape[2], dtype=lens.dtype)
    mask = cols[None, :] < lens[:, None]       # ragged, per sequence
    scores = jnp.where(mask[:, None, :], scores, -1e9)
    att = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('shk,shkd->shd', att, v)
