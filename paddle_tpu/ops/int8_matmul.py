"""Dynamic-quantized int8 matmul for TPU inference.

Reference analogue: the reference serves int8 via PaddleSlim +
TensorRT/cuDNN int8 kernels (fluid/contrib/slim); the TPU-native
equivalent feeds the MXU's native int8 path through a plain
lax.dot_general — no custom kernel needed, and the int8 weights stay
int8 in HBM (half the bytes of bf16), which is what matters on the
weight-bandwidth-bound decode step.

Scheme: per-output-channel weight scales (symmetric), per-tensor
dynamic activation scale computed on the fly (abs-max / 127).  The
int32 accumulator is rescaled by (x_scale * w_scale[o]).
"""
import jax
import jax.numpy as jnp

__all__ = ['quantize_weight_int8', 'dynamic_int8_matmul',
           'quantize_weight_int4_packed', 'unpack_int4',
           'dynamic_int4_matmul', 'artifact_to_matmul_scale']


def artifact_to_matmul_scale(scale, qmax=127):
    """Convert a paddle_tpu.quantization .quant artifact's
    per-channel (scale, qmax) pair — dequant there is q*scale/qmax —
    into the combined multiplier this op expects (dequant here is
    q*w_scale).  Keeps the two quantization grids interoperable."""
    return jnp.asarray(scale, jnp.float32) / float(qmax)


def quantize_weight_int8(w):
    """[H, O] float -> (int8 [H, O], f32 scales [O]) per-out-channel
    symmetric abs-max."""
    w = jnp.asarray(w)
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dynamic_int8_matmul(x, w_q, w_scale, bias=None,
                        out_dtype=jnp.bfloat16):
    """x [..., H] float @ dequant(w_q [H, O]) with dynamic per-tensor
    activation quantization.  The dot runs int8 x int8 -> int32 on the
    MXU; both operands stream from HBM at 1 byte per element."""
    xf = x.astype(jnp.float32)
    x_scale = jnp.maximum(jnp.max(jnp.abs(xf)) / 127.0, 1e-12)
    x_q = jnp.clip(jnp.round(xf / x_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale * w_scale)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype)


# -- packed int4 weights (two nibbles per byte) -------------------------------
#
# The PTQ artifact's 4x-compression backend: weights quantize onto the
# symmetric int4 grid (qmax=7, per-out-channel abs-max scales like the
# int8 path) and PACK two H-rows per uint8 — a quarter of bf16's HBM
# bytes on the weight-bandwidth-bound decode step.  The kernel unpacks
# nibbles to int8 in-register and runs the SAME int8 x int8 -> int32
# dot, so the int4 path is bit-identical to an int8 dot over the
# unpacked values (pinned by the parity test).

_Q4MAX = 7.0


def quantize_weight_int4_packed(w):
    """[H, O] float -> (packed uint8 [ceil(H/2), O], f32 scales [O]).
    Per-out-channel symmetric abs-max on the int4 grid; even H-row in
    the low nibble, odd H-row in the high nibble (zero-padded when H
    is odd — a zero row contributes nothing to the dot)."""
    w = jnp.asarray(w)
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0) / _Q4MAX
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]),
                 -_Q4MAX, _Q4MAX).astype(jnp.int8)
    if q.shape[0] % 2:
        q = jnp.concatenate(
            [q, jnp.zeros((1, q.shape[1]), jnp.int8)], axis=0)
    lo = q[0::2].astype(jnp.uint8) & 0xF
    hi = q[1::2].astype(jnp.uint8) & 0xF
    return (hi << 4) | lo, scale


def unpack_int4(packed, rows):
    """uint8 [P, O] -> int8 [rows, O]: split nibbles, sign-extend,
    re-interleave the H rows.  Lossless inverse of the packer."""
    def sext(v):
        v = v.astype(jnp.int8)
        return jnp.where(v >= 8, v - 16, v)

    lo = sext(packed & 0xF)
    hi = sext((packed >> 4) & 0xF)
    q = jnp.stack([lo, hi], axis=1).reshape(-1, packed.shape[1])
    return q[:rows]


def dynamic_int4_matmul(x, w_packed, w_scale, rows=None, bias=None,
                        out_dtype=jnp.bfloat16):
    """x [..., H] float @ dequant(int4-packed weight): nibbles unpack
    in the kernel, then the identical int8 dot as
    :func:`dynamic_int8_matmul` — the unpack fuses into the dot's
    operand read, the weight streams from HBM at half a byte per
    element.  ``rows`` is H (needed when H is odd; defaults to
    ``2 * w_packed.shape[0]``)."""
    rows = int(rows) if rows is not None else 2 * w_packed.shape[0]
    return dynamic_int8_matmul(x, unpack_int4(w_packed, rows),
                               w_scale, bias=bias,
                               out_dtype=out_dtype)
