"""Dynamic-quantized int8 matmul for TPU inference.

Reference analogue: the reference serves int8 via PaddleSlim +
TensorRT/cuDNN int8 kernels (fluid/contrib/slim); the TPU-native
equivalent feeds the MXU's native int8 path through a plain
lax.dot_general — no custom kernel needed, and the int8 weights stay
int8 in HBM (half the bytes of bf16), which is what matters on the
weight-bandwidth-bound decode step.

Scheme: per-output-channel weight scales (symmetric), per-tensor
dynamic activation scale computed on the fly (abs-max / 127).  The
int32 accumulator is rescaled by (x_scale * w_scale[o]).
"""
import jax
import jax.numpy as jnp

__all__ = ['quantize_weight_int8', 'dynamic_int8_matmul',
           'artifact_to_matmul_scale']


def artifact_to_matmul_scale(scale, qmax=127):
    """Convert a paddle_tpu.quantization .quant artifact's
    per-channel (scale, qmax) pair — dequant there is q*scale/qmax —
    into the combined multiplier this op expects (dequant here is
    q*w_scale).  Keeps the two quantization grids interoperable."""
    return jnp.asarray(scale, jnp.float32) / float(qmax)


def quantize_weight_int8(w):
    """[H, O] float -> (int8 [H, O], f32 scales [O]) per-out-channel
    symmetric abs-max."""
    w = jnp.asarray(w)
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dynamic_int8_matmul(x, w_q, w_scale, bias=None,
                        out_dtype=jnp.bfloat16):
    """x [..., H] float @ dequant(w_q [H, O]) with dynamic per-tensor
    activation quantization.  The dot runs int8 x int8 -> int32 on the
    MXU; both operands stream from HBM at 1 byte per element."""
    xf = x.astype(jnp.float32)
    x_scale = jnp.maximum(jnp.max(jnp.abs(xf)) / 127.0, 1e-12)
    x_q = jnp.clip(jnp.round(xf / x_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale * w_scale)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype)
