"""Shared token-sampling discipline for dense generate AND the paged
serving engine.

The load-bearing property is the **key discipline**: which PRNG key
samples which token.  Before PR 20 the two decode paths disagreed —
``generate`` split a key chain per step, the serving engine folded the
*intervention counter* into a batch-level key — so a ``temperature>0``
stream depended on batch composition and on WHEN the scheduler ran a
request, and could never be reproduced across engines.  That breaks
two things the serving front door needs:

* **sampled-decode parity** (ROADMAP serving remainder): the paged
  engine must produce the identical token stream as the dense
  ``generate`` path for the same seed;
* **retry replay** (PR-20 router): a request whose replica dies
  mid-stream is replayed on a survivor as ``prompt + emitted-prefix``
  — the continued tokens must be the ones the dead replica *would*
  have produced, or a failover silently changes user-visible output.

The shared discipline makes a sampled token a pure function of
``(request seed, absolute position)``:

    token sampled from the logits at absolute position ``pos`` of the
    row ``row`` uses ``row_key(PRNGKey(seed), pos, row)``
    = ``fold_in(fold_in(PRNGKey(seed), pos), row)``.

``generate`` shares one seed across its batch and distinguishes rows
by index; the serving engine gives every request its OWN per-request
key (derived from its rid — stable across replicas and retries) and
always uses ``row=0``, which is exactly what a batch-1 ``generate``
computes — so engine row ``i`` at position ``p`` and ``generate(seed)``
row 0 at position ``p`` draw the SAME key and the SAME token.  Replay
works for free: positions are absolute, so a re-prefilled
``prompt + prefix`` continues the original key sequence exactly.

Greedy (``temperature == 0``) ignores keys entirely and is unchanged.
"""
import jax
import jax.numpy as jnp

__all__ = ['row_key', 'sample_token', 'make_row_sampler',
           'sample_rows']


def row_key(base, pos, row=0):
    """The key that samples the token drawn from the logits at
    absolute position ``pos`` of batch row ``row``.  ``pos``/``row``
    may be traced ints (fold_in accepts them under jit)."""
    return jax.random.fold_in(jax.random.fold_in(base, pos), row)


def sample_token(logits, key, temperature, top_k):
    """Sample ONE token id from a single row of logits ``[V]``.

    The single-row primitive both decode paths vmap/call — one
    implementation, so the two paths can never drift numerically.
    Greedy (temperature 0/None) is the argmax and ignores the key.
    """
    greedy = temperature == 0 or temperature is None
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int64)
    lg = logits / jnp.asarray(temperature, logits.dtype)
    if top_k is not None:
        kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
        lg = jnp.where(lg < kth, -1e9, lg)
    return jax.random.categorical(key, lg, axis=-1).astype(jnp.int64)


def sample_rows(logits, base, pos, temperature, top_k):
    """generate()'s batch form: every row shares ``base`` (one seed
    per generate call) and ``pos`` (rows advance in lockstep);
    rows are distinguished by their index.  ``logits`` is ``[B, V]``;
    returns ``[B]`` int64."""
    greedy = temperature == 0 or temperature is None
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int64)
    B = logits.shape[0]
    keys = jax.vmap(lambda r: row_key(base, pos, r))(jnp.arange(B))
    return jax.vmap(
        lambda lg, k: sample_token(lg, k, temperature, top_k))(
            logits, keys)


def make_row_sampler(temperature, top_k):
    """The serving engine's per-request form: ``sample(logits[B, V],
    bases[B, 2], pos[B]) -> [B]`` where every row carries its OWN base
    key (its request's) and its OWN absolute position, and ``row=0``
    (per-request keys already distinguish rows — and row 0 is what a
    batch-1 generate uses, the parity contract)."""
    greedy = temperature == 0 or temperature is None

    def sample(logits, bases, pos):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int64)
        return jax.vmap(
            lambda lg, b, p: sample_token(
                lg, row_key(b, p, 0), temperature, top_k))(
                    logits, bases, pos)

    return sample
