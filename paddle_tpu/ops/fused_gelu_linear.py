"""Fused matmul + bias + GELU epilogue (Pallas), with a recompute-fused
backward.

Reference analogue: the reference's fused GEMM+activation CUDA ops
(paddle/fluid/operators/fused/, e.g. fused_gemm_epilogue /
fc_elementwise_layernorm); SURVEY.md §2 item 36's fourth kernel.

TPU-native design: the step is HBM-bound (see PERF.md), so the win is
NOT the epilogue itself (XLA fuses bias+GELU into the matmul already) —
it is the BACKWARD: instead of saving the [M, N] pre-activation z for
gelu'(z), the backward RE-computes z inside a second fused kernel that
emits dz = dy * gelu'(x@w + b) directly.  Residuals shrink from
(x, w, z) to (x, w, b): one full [M, N] HBM write + read traded for one
extra MXU matmul — the right trade on a bandwidth-bound chip.

    forward : y  = gelu(x @ w + b)          one kernel, no z in HBM
    backward: dz = dy * gelu'(x @ w + b)    one kernel, recomputes z
              dx = dz @ w.T ; dw = x.T @ dz ; db = sum(dz)   (XLA)
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _gating

__all__ = ['fused_linear_gelu']

_BM, _BN, _BK = 256, 256, 512

_SQRT_2_OVER_PI = math.sqrt(2.0 / math.pi)


def _gelu_f32(z, approximate):
    if approximate:
        inner = _SQRT_2_OVER_PI * (z + 0.044715 * z * z * z)
        return 0.5 * z * (1.0 + jnp.tanh(inner))
    return 0.5 * z * (1.0 + jax.lax.erf(z / math.sqrt(2.0)))


def _gelu_grad_f32(z, approximate):
    if approximate:
        inner = _SQRT_2_OVER_PI * (z + 0.044715 * z * z * z)
        t = jnp.tanh(inner)
        sech2 = 1.0 - t * t
        dinner = _SQRT_2_OVER_PI * (1.0 + 3 * 0.044715 * z * z)
        return 0.5 * (1.0 + t) + 0.5 * z * sech2 * dinner
    cdf = 0.5 * (1.0 + jax.lax.erf(z / math.sqrt(2.0)))
    pdf = jnp.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
    return cdf + z * pdf


def _reference(x, w, b, approximate):
    z = (x @ w).astype(jnp.float32)
    if b is not None:
        z = z + b.astype(jnp.float32)
    return _gelu_f32(z, approximate).astype(x.dtype)


def _accumulate(x_ref, w_ref, acc_ref, k):
    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(x_ref[:], w_ref[:],
                          preferred_element_type=jnp.float32)


def _fwd_kernel(x_ref, w_ref, b_ref, o_ref, acc_ref, *, nk, approximate):
    """y = gelu(x @ w + b): f32 VMEM accumulator, epilogue on the last
    K step — the pre-activation never touches HBM."""
    k = pl.program_id(2)
    _accumulate(x_ref, w_ref, acc_ref, k)

    @pl.when(k == nk - 1)
    def _epilogue():
        z = acc_ref[:] + b_ref[:].astype(jnp.float32)
        o_ref[:] = _gelu_f32(z, approximate).astype(o_ref.dtype)


def _bwd_kernel(x_ref, w_ref, b_ref, dy_ref, o_ref, acc_ref, *, nk,
                approximate):
    """dz = dy * gelu'(x @ w + b): recomputes z instead of reading a
    saved copy from HBM."""
    k = pl.program_id(2)
    _accumulate(x_ref, w_ref, acc_ref, k)

    @pl.when(k == nk - 1)
    def _epilogue():
        z = acc_ref[:] + b_ref[:].astype(jnp.float32)
        dy = dy_ref[:].astype(jnp.float32)
        o_ref[:] = (dy * _gelu_grad_f32(z, approximate)) \
            .astype(o_ref.dtype)


def _mm_epilogue(x, w, b, dy, approximate, bm, bn, bk):
    M, K = x.shape
    _, N = w.shape
    nk = K // bk
    grid = (M // bm, N // bn, nk)
    in_specs = [
        pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
        pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        # bias rides as (1, N): Mosaic rejects 1-D bf16 operands whose
        # XLA tiling disagrees with the kernel's (seen on v5e), and a 2-D
        # row broadcasts against the (bm, bn) accumulator for free
        pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
    ]
    operands = [x, w, b.reshape(1, -1)]
    if dy is None:
        kernel = functools.partial(_fwd_kernel, nk=nk,
                                   approximate=approximate)
    else:
        kernel = functools.partial(_bwd_kernel, nk=nk,
                                   approximate=approximate)
        in_specs.append(pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)))
        operands.append(dy)
    return pl.pallas_call(
        kernel,
        interpret=_gating.INTERPRET,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
    )(*operands)


def _pick_blocks(M, K, N):
    def fit(dim, pref):
        b = pref
        while b > 128 and dim % b != 0:
            b //= 2
        return b if dim % b == 0 else None

    bm = fit(M, _BM)
    bn = fit(N, _BN)
    bk = fit(K, _BK)
    if None in (bm, bn, bk):
        return None
    return bm, bn, bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused(x2d, w, b, approximate, blocks):
    bm, bn, bk = blocks
    return _mm_epilogue(x2d, w, b, None, approximate, bm, bn, bk)


def _fused_fwd(x2d, w, b, approximate, blocks):
    return _fused(x2d, w, b, approximate, blocks), (x2d, w, b)


def _fused_bwd(approximate, blocks, res, dy):
    x2d, w, b = res
    bm, bn, bk = blocks
    dz = _mm_epilogue(x2d, w, b, dy, approximate, bm, bn, bk)
    dzf = dz.astype(jnp.float32)
    dx = (dz @ w.T).astype(x2d.dtype)
    dw = (x2d.T @ dz).astype(w.dtype)
    db = dzf.sum(axis=0).astype(b.dtype)
    return dx, dw, db


_fused.defvjp(_fused_fwd, _fused_bwd)


USE_PALLAS_MLP = False  # measured on v5e: the Pallas kernel runs the
# [8192, 768]x[768, 3072] bf16 MLP at 5.2 TFLOPS vs XLA's 11.1 — XLA's
# own matmul+epilogue fusion wins at transformer shapes (PERF.md), so
# the kernel stays opt-in (flip this, or call fused_linear_gelu
# directly) and the default path lets the compiler fuse.


def mlp_gelu(x, fc, shard_spec=None):
    """Shared model-side dispatch for the fc+GELU half of a transformer
    MLP: XLA matmul + fused GELU epilogue by default (measured faster
    than the hand-written kernel — see USE_PALLAS_MLP); under a mesh the
    tp-sharded column-parallel path additionally applies shardings.

    x: Tensor [..., H]; fc: a Linear-like Layer with .weight/.bias;
    shard_spec: the activation PartitionSpec for the mesh path."""
    from ..distributed import env as _env
    from ..core.dispatch import apply
    if USE_PALLAS_MLP and _env.get_mesh() is None:
        return apply(lambda xv, wv, bv: fused_linear_gelu(
            xv, wv, bv, approximate=True),
            x, fc.weight, fc.bias, op_name='fused_linear_gelu')
    from ..nn import functional as F
    from ..parallel.api import maybe_shard
    h = fc(x)
    if shard_spec is not None:
        h = maybe_shard(h, shard_spec)   # identity without a mesh
    return F.gelu(h, approximate=True)


def fused_linear_gelu(x, w, b, approximate=True):
    """gelu(x @ w + b) with the fused Pallas path on TPU.

    x: [..., K]; w: [K, N]; b: [N].  Falls back to the jnp reference
    off-TPU, under a mesh, or for non-tileable shapes.
    """
    from ._gating import pallas_backend_ok
    K = x.shape[-1]
    N = w.shape[-1]
    lead = x.shape[:-1]
    M = 1
    for s in lead:
        M *= s
    blocks = _pick_blocks(M, K, N)
    if not (pallas_backend_ok() and b is not None and blocks):
        return _reference(x, w, b, approximate)
    y = _fused(x.reshape(M, K), w, b, approximate, blocks)
    return y.reshape(lead + (N,))
