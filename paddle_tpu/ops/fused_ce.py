"""Fused linear + softmax cross-entropy head.

Reference analogue: the reference fuses softmax+CE
(softmax_with_cross_entropy,
/root/reference/python/paddle/nn/functional/loss.py and the
softmax_with_cross_entropy_op.cu kernel) but still materializes the
full [N, V] logits from the LM head matmul.

TPU-native: the head matmul itself is fused INTO the loss.  The f32
[N, V] logits tensor — at GPT-2 scale (8x1024, 50257) ≈ 1.6 GB of HBM
traffic per step for logits+softmax+grad — is never written.  The
vocab dimension is processed in chunks with an ONLINE logsumexp
(the flash-attention recurrence applied to the vocab axis):

    m' = max(m, max_j z_j)       s' = s·e^(m-m') + Σ_j e^(z_j - m')

per chunk, plus a label-logit gather.  Each chunk is one
[N, H] x [H, Vc] MXU matmul (bf16 inputs, f32 accumulation via
preferred_element_type) followed by elementwise work XLA fuses into
it; live memory is [N, Vc].  The backward recomputes each chunk's
logits (flash-style rematerialisation — FLOPs are cheap, HBM is not)
and emits dx and dw chunkwise.

ONE recurrence serves both heads: the single-device op is the
column-offset-0 case of the core; the tensor-parallel op
(`fused_linear_cross_entropy_tp`, for shard_map contexts like the
pipeline engine) runs the same core on its vocab shard at offset
r*Vs and composes the (max, sumexp, label-logit) triples across the
axis with one pmax + two psums — the ParallelCrossEntropy contract,
fused with the matmul.

Exact to the unfused computation up to f32 associativity: the
correctness tests assert ≤1e-5 against log_softmax on the
materialized logits, including shard-boundary and ragged-chunk
labels.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['fused_linear_cross_entropy',
           'fused_linear_cross_entropy_tp']


def _varying(v, axis):
    """Mark a replicated value as axis-varying for shard_map's
    manual-axes check (pvary was renamed to pcast).  Pre-VMA jax has
    neither primitive AND no varying-type check — nothing to mark."""
    if axis is None:
        return v
    if hasattr(lax, 'pcast'):
        try:
            return lax.pcast(v, to='varying')
        except TypeError:
            pass
    if hasattr(lax, 'pvary'):
        return lax.pvary(v, axis)
    return v


def _chunk_w(w, num_chunks):
    H, V = w.shape
    Vc = -(-V // num_chunks)
    pad = num_chunks * Vc - V
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
    return w.reshape(H, num_chunks, Vc).transpose(1, 0, 2), Vc, pad


def _scan_core(x, w, labels, num_chunks, col0, axis=None):
    """Online logsumexp over w's columns (one shard's slice of the
    full vocab, starting at GLOBAL column col0).  Returns (m, s, zl):
    running max, sumexp (relative to m), and this shard's label-logit
    contribution (zero when the label belongs to another shard)."""
    N = x.shape[0]
    V = w.shape[1]
    wc, Vc, _ = _chunk_w(w, num_chunks)
    # this shard owns GLOBAL ids [col0, col0 + V)
    local = labels - col0
    owned = (local >= 0) & (local < V)

    def body(carry, args):
        m, s, zl = carry
        w_c, c = args
        z = jnp.dot(x, w_c,
                    preferred_element_type=jnp.float32)   # [N, Vc]
        # padded chunk columns (V % num_chunks != 0) must not leak
        # zeros into the logsumexp — and a label owned by the NEXT
        # shard must not gather from this shard's pad cells
        valid = (c * Vc + jnp.arange(Vc)) < V
        z = jnp.where(valid[None, :], z, -jnp.inf)
        new_m = jnp.maximum(m, jnp.max(z, axis=-1))
        s = s * jnp.exp(m - new_m) \
            + jnp.sum(jnp.exp(z - new_m[:, None]), axis=-1)
        loc = local - c * Vc
        mine = owned & (loc >= 0) & (loc < Vc)
        zl = zl + jnp.where(
            mine,
            jnp.take_along_axis(
                z, jnp.clip(loc, 0, Vc - 1)[:, None], axis=1)[:, 0],
            0.0)
        return (new_m, s, zl), None

    init = (jnp.full((N,), -jnp.inf, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.zeros((N,), jnp.float32))
    init = jax.tree_util.tree_map(lambda v: _varying(v, axis), init)
    (m, s, zl), _ = lax.scan(
        body, init, (wc, jnp.arange(num_chunks)))
    return m, s, zl


def _bwd_core(x, w, labels, lse, g, num_chunks, col0, axis=None):
    """Chunked recompute backward for one shard's columns: returns
    (dx_partial, dw).  dx_partial covers only this shard's columns —
    the tp caller psums it over the axis."""
    N = x.shape[0]
    V = w.shape[1]
    wc, Vc, pad = _chunk_w(w, num_chunks)
    local = labels - col0
    owned = (local >= 0) & (local < V)

    def body(dx, args):
        w_c, c = args
        z = jnp.dot(x, w_c, preferred_element_type=jnp.float32)
        valid = (c * Vc + jnp.arange(Vc)) < V
        p = jnp.where(valid[None, :],
                      jnp.exp(z - lse[:, None]), 0.0)      # [N, Vc]
        loc = local - c * Vc
        mine = owned & (loc >= 0) & (loc < Vc)
        # dense one-hot subtraction: the .at[].add element scatter here
        # serialized on TPU (HLO census round 4 — 8184 single-f32
        # updates per chunk); the iota compare fuses into the epilogue
        oh = (loc[:, None] == jnp.arange(Vc)[None, :]) & mine[:, None]
        p = p - oh.astype(p.dtype)
        d = p * g[:, None]                                  # [N, Vc]
        dw_c = jnp.dot(x.astype(jnp.float32).T, d,
                       preferred_element_type=jnp.float32)
        dx = dx + jnp.dot(d, w_c.astype(jnp.float32).T,
                          preferred_element_type=jnp.float32)
        return dx, dw_c

    dx0 = _varying(jnp.zeros((N, x.shape[1]), jnp.float32), axis)
    dx, dw_chunks = lax.scan(
        body, dx0, (wc, jnp.arange(num_chunks)))
    dw = dw_chunks.transpose(1, 0, 2).reshape(x.shape[1], -1)
    if pad:
        dw = dw[:, :V]
    return dx, dw


def _label_ct(labels):
    import numpy as np
    return np.zeros(np.shape(labels), jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(x, w, labels, num_chunks=8):
    """Per-example CE of softmax(x @ w) against integer labels,
    WITHOUT materializing the [N, V] logits.

    x: [N, H] (any float dtype; bf16 recommended), w: [H, V],
    labels: [N] int.  Returns f32 [N] losses (caller reduces).
    `num_chunks` (static) splits V; live memory is [N, ceil(V/num_
    chunks)].
    """
    m, s, zl = _scan_core(x, w, labels, num_chunks, 0)
    return (jnp.log(s) + m) - zl


def _fwd(x, w, labels, num_chunks):
    m, s, zl = _scan_core(x, w, labels, num_chunks, 0)
    lse = jnp.log(s) + m
    return lse - zl, (x, w, labels, lse)


def _bwd(num_chunks, res, g):
    x, w, labels, lse = res
    dx, dw = _bwd_core(x, w, labels, lse, g, num_chunks, 0)
    return dx.astype(x.dtype), dw.astype(w.dtype), _label_ct(labels)


fused_linear_cross_entropy.defvjp(_fwd, _bwd)


def fused_linear_cross_entropy_tp(x, w_shard, labels, axis='tp',
                                  num_chunks=4):
    """Vocab-PARALLEL fused head for shard_map contexts (the pipeline
    engine, manual tp): each shard holds w_shard [H, V/tp] — the
    columns [r*Vs, (r+1)*Vs) of the full weight for axis index r.

    x [N, H] replicated over `axis`; labels [N] GLOBAL ids,
    replicated.  Returns per-example f32 losses [N], replicated.
    Differentiable: the backward recomputes local chunk logits; dx
    psums over the axis, dW stays shard-local.
    """
    Vs = w_shard.shape[1]

    def _shard_col0():
        return lax.axis_index(axis) * Vs

    @jax.custom_vjp
    def _op(xv, wv, yv):
        loss, _ = _tp_fwd(xv, wv, yv)
        return loss

    def _tp_fwd(xv, wv, yv):
        col0 = _shard_col0()
        m, s, zl = _scan_core(xv, wv, yv, num_chunks, col0,
                              axis=axis)
        # compose the shard-local (max, sumexp) pairs globally
        M = lax.pmax(m, axis)
        S = lax.psum(s * jnp.exp(m - M), axis)
        lse = jnp.log(S) + M
        zl_g = lax.psum(zl, axis)   # the label lives in ONE shard
        return lse - zl_g, lse

    def _fwd_tp(xv, wv, yv):
        loss, lse = _tp_fwd(xv, wv, yv)
        return loss, (xv, wv, yv, lse)

    def _bwd_tp(res, g):
        xv, wv, yv, lse = res
        dx, dw = _bwd_core(xv, wv, yv, lse, g, num_chunks,
                           _shard_col0(), axis=axis)
        # x is replicated over the axis but each shard saw only its
        # vocab columns: the full dz @ W^T sums over shards
        dx = lax.psum(dx, axis)
        return dx.astype(xv.dtype), dw.astype(wv.dtype), \
            _label_ct(yv)

    _op.defvjp(_fwd_tp, _bwd_tp)
    return _op(x, w_shard, labels)
