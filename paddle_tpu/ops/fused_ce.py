"""Fused linear + softmax cross-entropy head.

Reference analogue: the reference fuses softmax+CE
(softmax_with_cross_entropy,
/root/reference/python/paddle/nn/functional/loss.py and the
softmax_with_cross_entropy_op.cu kernel) but still materializes the
full [N, V] logits from the LM head matmul.

TPU-native: the head matmul itself is fused INTO the loss.  The f32
[N, V] logits tensor — at GPT-2 scale (8x1024, 50257) ≈ 1.6 GB of HBM
traffic per step for logits+softmax+grad — is never written.  The
vocab dimension is processed in chunks with an ONLINE logsumexp
(the flash-attention recurrence applied to the vocab axis):

    m' = max(m, max_j z_j)       s' = s·e^(m-m') + Σ_j e^(z_j - m')

per chunk, plus a label-logit gather.  Each chunk is one
[N, H] x [H, Vc] MXU matmul (bf16 inputs, f32 accumulation via
preferred_element_type) followed by elementwise work XLA fuses into
it; live memory is [N, Vc].  The backward recomputes each chunk's
logits (flash-style rematerialisation — FLOPs are cheap, HBM is not)
and emits dx and dw chunkwise.

Exact to the unfused computation up to f32 associativity: the
correctness tests assert ≤1e-5 against log_softmax on the
materialized logits.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['fused_linear_cross_entropy']


def _chunk_w(w, num_chunks):
    H, V = w.shape
    Vc = -(-V // num_chunks)
    pad = num_chunks * Vc - V
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
    return w.reshape(H, num_chunks, Vc).transpose(1, 0, 2), Vc, pad


def _fwd_scan(x, w, labels, num_chunks):
    N, H = x.shape
    V = w.shape[1]
    wc, Vc, pad = _chunk_w(w, num_chunks)
    xf = x

    def body(carry, args):
        m, s, zl = carry
        w_c, c = args
        z = jnp.dot(xf, w_c,
                    preferred_element_type=jnp.float32)   # [N, Vc]
        col0 = c * Vc
        valid = (col0 + jnp.arange(Vc)) < V
        z = jnp.where(valid[None, :], z, -jnp.inf)
        new_m = jnp.maximum(m, jnp.max(z, axis=-1))
        s = s * jnp.exp(m - new_m) \
            + jnp.sum(jnp.exp(z - new_m[:, None]), axis=-1)
        # label logit if it lives in this chunk
        loc = labels - col0
        mine = (loc >= 0) & (loc < Vc)
        zl = zl + jnp.where(
            mine,
            jnp.take_along_axis(
                z, jnp.clip(loc, 0, Vc - 1)[:, None], axis=1)[:, 0],
            0.0)
        return (new_m, s, zl), None

    init = (jnp.full((N,), -jnp.inf, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.zeros((N,), jnp.float32))
    (m, s, zl), _ = lax.scan(
        body, init, (wc, jnp.arange(num_chunks)))
    lse = jnp.log(s) + m
    return lse - zl, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(x, w, labels, num_chunks=8):
    """Per-example CE of softmax(x @ w) against integer labels,
    WITHOUT materializing the [N, V] logits.

    x: [N, H] (any float dtype; bf16 recommended), w: [H, V],
    labels: [N] int.  Returns f32 [N] losses (caller reduces).
    `num_chunks` (static) splits V; live memory is [N, ceil(V/num_
    chunks)].
    """
    loss, _ = _fwd_scan(x, w, labels, num_chunks)
    return loss


def _fwd(x, w, labels, num_chunks):
    loss, lse = _fwd_scan(x, w, labels, num_chunks)
    return loss, (x, w, labels, lse)


def _bwd(num_chunks, res, g):
    x, w, labels, lse = res
    N, H = x.shape
    V = w.shape[1]
    wc, Vc, pad = _chunk_w(w, num_chunks)

    def body(dx, args):
        w_c, c = args
        z = jnp.dot(x, w_c, preferred_element_type=jnp.float32)
        col0 = c * Vc
        valid = (col0 + jnp.arange(Vc)) < V
        p = jnp.where(valid[None, :],
                      jnp.exp(z - lse[:, None]), 0.0)      # [N, Vc]
        loc = labels - col0
        mine = (loc >= 0) & (loc < Vc)
        onehot_col = jnp.clip(loc, 0, Vc - 1)
        p = p.at[jnp.arange(N), onehot_col].add(
            jnp.where(mine, -1.0, 0.0))
        d = p * g[:, None]                                  # [N, Vc]
        # dW chunk: [H, Vc]; dx accumulates over chunks
        dw_c = jnp.dot(x.astype(jnp.float32).T, d,
                       preferred_element_type=jnp.float32)
        dx = dx + jnp.dot(d, w_c.astype(jnp.float32).T,
                          preferred_element_type=jnp.float32)
        return dx, dw_c

    dx0 = jnp.zeros((N, H), jnp.float32)
    dx, dw_chunks = lax.scan(
        body, dx0, (wc, jnp.arange(num_chunks)))
    dw = dw_chunks.transpose(1, 0, 2).reshape(H, -1)
    if pad:
        dw = dw[:, :V]
    import numpy as np
    ct = np.zeros(np.shape(labels), jax.dtypes.float0)
    return dx.astype(x.dtype), dw.astype(w.dtype), ct


fused_linear_cross_entropy.defvjp(_fwd, _bwd)
