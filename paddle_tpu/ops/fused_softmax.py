"""Fused (optionally masked) softmax (Pallas forward, y-reusing backward).

Reference analogue: softmax_op.cu / fused softmax-with-mask kernels in
the reference; one VMEM pass on TPU.  SURVEY.md §2 item 36.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import _gating

__all__ = ['fused_softmax']

_BLOCK_ROWS = 256


def _reference(x, mask):
    xf = x.astype(jnp.float32)
    if mask is not None:
        xf = xf + mask.astype(jnp.float32)
    return jax.nn.softmax(xf, axis=-1).astype(x.dtype)


def _kernel(x_ref, y_ref):
    x = x_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    y_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def _masked_kernel(x_ref, mask_ref, y_ref):
    x = x_ref[:].astype(jnp.float32) + mask_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    y_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def _fwd_pallas(x2d, mask2d, block_rows):
    n, h = x2d.shape
    grid = (n // block_rows,)
    if mask2d is None:
        return pl.pallas_call(
            _kernel,
            interpret=_gating.INTERPRET,
            grid=grid,
            in_specs=[pl.BlockSpec((block_rows, h), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, h), x2d.dtype),
        )(x2d)
    return pl.pallas_call(
        _masked_kernel,
        interpret=_gating.INTERPRET,
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
                  pl.BlockSpec((block_rows, h), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x2d.dtype),
    )(x2d, mask2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _sm(x2d, mask2d, block_rows):
    return _fwd_pallas(x2d, mask2d, block_rows)


def _sm_fwd(x2d, mask2d, block_rows):
    y = _fwd_pallas(x2d, mask2d, block_rows)
    return y, (y, mask2d is not None)


def _sm_bwd(block_rows, res, g):
    (y, had_mask) = res
    yf = y.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    dx = yf * (gf - jnp.sum(gf * yf, axis=-1, keepdims=True))
    dx = dx.astype(y.dtype)
    # d/dmask of softmax(x + mask) equals d/dx
    return dx, (dx if had_mask else None)


_sm.defvjp(_sm_fwd, _sm_bwd)


def fused_softmax(x, mask=None, block_rows=_BLOCK_ROWS):
    """Softmax over the last axis (+ optional additive mask);
    Pallas-fused on TPU, jnp fallback elsewhere."""
    h = x.shape[-1]
    n = 1
    for s in x.shape[:-1]:
        n *= s
    from ._gating import pallas_backend_ok, pick_block_rows
    br = pick_block_rows(n, block_rows)
    if not (pallas_backend_ok() and h % 128 == 0 and br):
        return _reference(x, mask)
    m2d = None
    if mask is not None:
        m2d = jnp.broadcast_to(mask, x.shape).reshape(n, h)
    return _sm(x.reshape(n, h), m2d, br).reshape(x.shape)
