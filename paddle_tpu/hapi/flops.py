"""Analytic FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py).

Counts multiply-accumulates as 1 FLOP each (the reference's convention)
for the common layer types via forward hooks on a dummy forward.
"""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from .. import nn

__all__ = ['flops']


def _prod(s):
    return int(np.prod(s)) if len(s) else 1


def _count(layer, inp, out):
    in_shape = inp[0].shape if inp else []
    out_shape = out.shape if isinstance(out, Tensor) else \
        (out[0].shape if isinstance(out, (list, tuple)) and out else [])
    if isinstance(layer, (nn.Conv1D, nn.Conv2D, nn.Conv3D,
                          nn.Conv1DTranspose, nn.Conv2DTranspose,
                          nn.Conv3DTranspose)):
        kernel_ops = _prod(layer.kernel_size) * \
            (layer.in_channels // layer.groups)
        bias_ops = 1 if layer.bias is not None else 0
        return _prod(out_shape) * (kernel_ops + bias_ops)
    if isinstance(layer, nn.Linear):
        batch = _prod(in_shape[:-1])
        out_f = layer.weight.shape[-1]
        bias_ops = out_f if layer.bias is not None else 0
        return batch * (in_shape[-1] * out_f + bias_ops)
    if isinstance(layer, (nn.BatchNorm, nn.BatchNorm1D, nn.BatchNorm2D,
                          nn.BatchNorm3D, nn.LayerNorm, nn.GroupNorm)):
        return 2 * _prod(in_shape)
    if isinstance(layer, (nn.ReLU, nn.ReLU6, nn.Sigmoid, nn.Softmax,
                          nn.GELU, nn.Tanh)):
        return _prod(in_shape)
    if isinstance(layer, (nn.AvgPool1D, nn.AvgPool2D, nn.AvgPool3D,
                          nn.MaxPool1D, nn.MaxPool2D, nn.MaxPool3D,
                          nn.AdaptiveAvgPool1D, nn.AdaptiveAvgPool2D,
                          nn.AdaptiveAvgPool3D)):
        return _prod(out_shape)
    return 0


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total forward FLOPs for `net` on `input_size` (list incl. batch)."""
    custom_ops = custom_ops or {}
    total = [0]
    rows = []
    hooks = []

    def add_hooks(layer, prefix=''):
        subs = list(layer._sub_layers.items())
        if not subs:
            def hook(l, inp, out, name=prefix):
                fn = custom_ops.get(type(l))
                n = fn(l, inp, out) if fn else _count(l, inp, out)
                total[0] += n
                rows.append((name or l.__class__.__name__, n))
            hooks.append(layer.register_forward_post_hook(hook))
        for name, sub in subs:
            add_hooks(sub, f'{prefix}.{name}' if prefix else name)

    add_hooks(net)
    x = Tensor(jnp.zeros(input_size, dtype='float32'))
    was_training = net.training
    net.eval()
    try:
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    if print_detail:
        for name, n in rows:
            print(f'{name:<50}{n:>16,}')
        print(f"{'Total FLOPs':<50}{total[0]:>16,}")
    return total[0]
