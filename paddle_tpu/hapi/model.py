"""High-level Model API: fit / evaluate / predict / save / load.

Reference analogue: python/paddle/hapi/model.py (class Model).  The
reference dispatches per-batch through the dygraph tracer or a static
Program; here `fit` compiles ONE jitted train step — forward + loss +
grad + optimizer update + metric pre-compute — into a single XLA module
with donated params/opt-state (in-place HBM update), and the epoch loop
stays host-side.  That is the whole TPU story: the MXU sees one fused
program per step, the host only feeds batches.
"""
import os
import signal as _signal
import warnings

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..jit import functional_call
from ..io import DataLoader, Dataset
from ..framework.io import save as _save, load as _load
from ..metric import Metric
from ..resilience import (
    finite_step as _finite_step, guard_update as _guard_update,
    install_shutdown as _install_shutdown,
    shutdown_requested as _shutdown_requested)
from .callbacks import config_callbacks

__all__ = ['Model']


def _to_jnp(x):
    if isinstance(x, Tensor):
        return x.value
    return jnp.asarray(x)


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _outs_list(outs):
    """functional_call returns the layer's output pytree verbatim — a bare
    array for single-output layers; normalize to a list."""
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


class Model:
    """Wraps a Layer with train/eval/predict loops over compiled steps.

    Args:
        network: paddle_tpu.nn.Layer with forward(*inputs).
        inputs/labels: optional InputSpec lists (count determines the
            input/label split of each batch; default 1 label).
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _as_list(inputs)
        self._labels = _as_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._lint = None
        self.stop_training = False
        # True (default): train_batch materializes the per-step
        # finiteness flag so skipped steps feed no metrics and
        # NanGuard sees a Python bool — one host sync per step, the
        # price of the exact skip contract.  NanGuard(enable=False)
        # flips this off for the sync-free fast path: the loss / ok
        # stay device arrays, the step counter advances on device, and
        # skipped steps contribute zeroed (masked) metric stats.
        self._check_finite_steps = True
        # compiled-step caches, keyed by (shapes, dtypes, lr-if-constant)
        self._train_step_cache = {}
        self._train_chunk_cache = {}    # fused K-step modules
        self._eval_step_cache = {}
        self._pred_step_cache = {}
        # functional state lives here between steps (device pytrees)
        self._fstate = None
        # divergence sentinel plumbing: last-known-good snapshot for
        # rollback + the per-step finiteness flag NanGuard reads
        self._good_state = None
        self._last_step_ok = True

    # -- preparation ---------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, lint=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _as_list(metrics)
        for m in self._metrics:
            assert isinstance(m, Metric), \
                'metrics must be paddle_tpu.metric.Metric instances'
        self._amp = amp_configs or {}
        # lint: run the paddle_tpu.analysis TPU lint over each newly
        # compiled train step (jaxpr rules incl. donation audit) and
        # over the network's forward source — None/False off,
        # 'warn'/True warns, 'error' raises on high severity
        self._lint = lint
        # a new optimizer/loss invalidates compiled steps (their traces
        # closed over the old ones) and the functional state
        self._train_step_cache.clear()
        self._train_chunk_cache.clear()
        self._eval_step_cache.clear()
        self._pred_step_cache.clear()
        self._invalidate()
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    # -- functional state sync -----------------------------------------------
    def _get_fstate(self):
        if self._fstate is None:
            params, buffers = self.network.functional_state()
            # copy: the compiled step donates its inputs, and these arrays
            # are aliased by the live eager Parameters
            params = jax.tree_util.tree_map(
                lambda v: jnp.array(v, copy=True), params)
            buffers = jax.tree_util.tree_map(
                lambda v: jnp.array(v, copy=True), buffers)
            if self._optimizer is not None:
                # resume from eager accumulators (set by load()) when present
                live = dict(self.network.named_parameters())
                acc = self._optimizer._accumulators
                opt_state = {
                    n: jax.tree_util.tree_map(
                        lambda v: jnp.array(v, copy=True), acc[id(p)])
                    if id(p) in acc
                    else self._optimizer._create_state(p.value)
                    for n, p in live.items()}
                step = self._optimizer._global_step
            else:
                opt_state, step = {}, 0
            self._fstate = {'params': params, 'buffers': buffers,
                            'opt': opt_state, 'step': step}
        return self._fstate

    def _sync_back(self):
        """Write device pytrees back into the eager Layer tree and the
        optimizer's accumulators (so state_dict/save see trained state).
        Copies: the next compiled step donates the fstate arrays."""
        if self._fstate is None:
            return
        cp = lambda v: jnp.array(v, copy=True)  # noqa: E731
        self.network.load_functional_state(
            jax.tree_util.tree_map(cp, self._fstate['params']),
            jax.tree_util.tree_map(cp, self._fstate['buffers']))
        if self._optimizer is not None:
            live = dict(self.network.named_parameters())
            for n, st in self._fstate['opt'].items():
                if n in live:
                    self._optimizer._accumulators[id(live[n])] = \
                        jax.tree_util.tree_map(cp, st)
            # the sync-free step path advances the counter on device;
            # materialize it here (an epoch/save boundary) so
            # state_dict round-trips a plain int
            self._optimizer._global_step = int(
                np.asarray(self._fstate['step']))

    def _invalidate(self):
        """Eager params changed (load/user edit): drop functional state."""
        self._fstate = None

    # -- divergence rollback (resilience.NanSentinel policy) -----------------
    def _copy_tree(self, t):
        return jax.tree_util.tree_map(
            lambda v: jnp.array(v, copy=True) if hasattr(v, 'dtype')
            else v, t)

    def _capture_good_state(self):
        """Snapshot the functional state as the rollback target.
        Copies are mandatory: the compiled step donates the live
        fstate arrays, so an aliased snapshot would be deleted out
        from under us by the very next step."""
        st = self._get_fstate()
        self._good_state = {'params': self._copy_tree(st['params']),
                            'buffers': self._copy_tree(st['buffers']),
                            'opt': self._copy_tree(st['opt']),
                            'step': st['step']}

    def _rollback_to_good_state(self):
        """Restore the last captured snapshot (NanGuard calls this
        after K consecutive non-finite steps).  -> True if a snapshot
        existed.  The snapshot itself is re-copied so repeated
        rollbacks keep working."""
        if self._good_state is None:
            return False
        g = self._good_state
        self._fstate = {'params': self._copy_tree(g['params']),
                        'buffers': self._copy_tree(g['buffers']),
                        'opt': self._copy_tree(g['opt']),
                        'step': g['step']}
        if self._optimizer is not None:
            self._optimizer._global_step = g['step']
        return True

    # -- compiled steps ------------------------------------------------------
    def _loss_value(self, outs, labels):
        outs_t = [Tensor._from_value(o) for o in outs]
        labels_t = [Tensor._from_value(l) for l in labels]
        if self._loss is None:
            lv = outs[0]
        else:
            lv = self._loss(*(outs_t + labels_t))
            lv = lv.value if isinstance(lv, Tensor) else jnp.asarray(lv)
        return jnp.mean(lv)

    def _metric_computes(self, outs, labels):
        res = []
        for m in self._metrics:
            if labels:
                r = m.compute(outs[0], labels[0])
            else:
                r = m.compute(outs[0])
            res.append(r.value if isinstance(r, Tensor) else r)
        return res

    def _batch_key(self, arrays, extra=()):
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        return sig + tuple(extra)

    def _build_train_step(self, n_in):
        """The raw (unjitted) step — also what prepare(lint=...)
        audits, so the linter sees exactly what XLA compiles."""
        network, opt = self.network, self._optimizer

        def step_fn(params, buffers, opt_state, base_key, prev_step, lr,
                    *arrays):
            inputs, labels = arrays[:n_in], arrays[n_in:]
            # the per-step dropout key (fold of paddle.seed with the
            # step counter) and the counter increment both live INSIDE
            # the module: the sync-free path then issues zero per-step
            # host-side dispatches beyond this one call
            step = prev_step + 1
            key = jax.random.fold_in(base_key, prev_step)

            def loss_fn(p):
                outs, new_buf = functional_call(
                    network, p, buffers, inputs, key=key, training=True)
                outs = _outs_list(outs)
                return self._loss_value(outs, labels), (outs, new_buf)

            (loss, (outs, new_buf)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            # divergence sentinel, device side: a non-finite
            # loss/grad step keeps the OLD params/opt/buffers — the
            # update is skipped inside the same XLA module, composing
            # with the amp GradScaler's found_inf skip on the eager
            # path.  Host-side policy (strike counting, rollback)
            # lives in callbacks.NanGuard.
            ok = _finite_step(loss, grads)
            # lr is a traced arg: scheduler steps / set_lr reach the
            # compiled module without retracing
            new_params, new_opt = opt.apply_gradients(
                params, grads, opt_state, step, lr=lr)
            new_params = _guard_update(ok, new_params, params)
            new_opt = _guard_update(ok, new_opt, opt_state)
            new_buf = _guard_update(ok, new_buf, buffers)
            # metric stats are masked ON DEVICE for skipped steps so
            # the sync-free path can feed them without reading `ok`
            # back (neutral adds for count-style metrics)
            metrics = [jax.tree_util.tree_map(
                lambda v: jnp.where(ok, v, jnp.zeros_like(v)), r)
                for r in self._metric_computes(outs, labels)]
            new_step = prev_step + ok.astype(jnp.int32)
            return (new_params, new_buf, new_opt, new_step, loss, ok,
                    metrics)

        return step_fn

    def _make_train_step(self, n_in):
        return jax.jit(self._build_train_step(n_in),
                       donate_argnums=(0, 1, 2))

    def _make_eval_step(self, n_in):
        network = self.network

        def step_fn(params, buffers, key, *arrays):
            inputs, labels = arrays[:n_in], arrays[n_in:]
            outs, _ = functional_call(network, params, buffers, inputs,
                                      key=key, training=False)
            outs = _outs_list(outs)
            loss = self._loss_value(outs, labels) \
                if self._loss is not None else jnp.zeros(())
            metrics = self._metric_computes(outs, labels)
            return outs, loss, metrics

        return jax.jit(step_fn)

    def _make_pred_step(self, n_in):
        network = self.network

        def step_fn(params, buffers, key, *arrays):
            outs, _ = functional_call(network, params, buffers,
                                      arrays[:n_in], key=key,
                                      training=False)
            return _outs_list(outs)

        return jax.jit(step_fn)

    def _split_arity(self, n_fields):
        """How many leading fields of an n_fields batch feed forward
        (the rest are labels) — shape logic only, no conversion."""
        n_lab = len(self._labels) if self._labels else \
            (1 if self._loss is not None else 0)
        n_lab = min(n_lab, max(0, n_fields - 1))
        return n_fields - n_lab

    def _split_batch(self, batch):
        batch = [_to_jnp(b) for b in _as_list(batch)]
        return batch, self._split_arity(len(batch))

    # -- public batch APIs ---------------------------------------------------
    def train_batch(self, inputs, labels=None):
        """One compiled optimizer step; returns (loss, metric_results).

        The loss comes back as a DEVICE scalar (host-sync lint: the
        old ``float(loss)`` here stalled the XLA queue every step —
        see PERF.md).  ``float(loss)`` still works for callers that
        want a number; the fit loop materializes only when a logger
        actually prints."""
        assert self._optimizer is not None and self._loss is not None, \
            'call prepare(optimizer, loss) before train_batch'
        batch = _as_list(inputs) + _as_list(labels)
        arrays, n_in = self._split_batch(batch)
        st = self._get_fstate()
        key = self._batch_key(arrays, ('train', n_in))
        first_call = key not in self._train_step_cache
        if first_call:
            if self._lint:
                self._lint_train_step(n_in, st, arrays)
            jitted = self._make_train_step(n_in)
            from ..core import compile_cache as _cc
            if _cc.enabled():
                # persistent executable cache (core.compile_cache): a
                # restarted process deserializes the exported step
                # instead of re-tracing; cold path unchanged (donating
                # jit) and additionally exported for the next process
                example = (st['params'], st['buffers'], st['opt'],
                           jax.random.PRNGKey(0),
                           jnp.zeros((), jnp.int32),
                           jnp.zeros((), jnp.float32), *arrays)
                fp = _cc.jaxpr_fingerprint(
                    'hapi-train', self._build_train_step(n_in), example,
                    extra=('donate', (0, 1, 2)))
                jitted = _cc.through_cache(jitted, example, fp=fp,
                                           name='Model.train_batch')
            # memory observatory, armed-only (one extra lower+compile
            # per variant): XLA memory_analysis vs liveness prediction
            from ..telemetry import memory as _mem
            _mem.ensure_sampler()
            if _mem.armed():
                _mem.maybe_note_compiled(
                    'Model.train_batch', jitted,
                    (st['params'], st['buffers'], st['opt'],
                     jax.random.PRNGKey(0), jnp.zeros((), jnp.int32),
                     jnp.zeros((), jnp.float32), *arrays),
                    source='hapi')
            self._train_step_cache[key] = jitted
            from ..analysis import note_retrace
            note_retrace('Model.train_batch',
                         len(self._train_step_cache), instance=self)
        fn = self._train_step_cache[key]
        # base dropout key derived from the user's paddle.seed (the
        # engine's core.rng) — the per-step fold with the counter
        # happens inside the compiled module; cache the PRNGKey until
        # the user reseeds
        from ..core import rng as rng_mod
        seed = rng_mod.get_seed()
        if getattr(self, '_base_key_seed', None) != seed:
            self._base_key = jax.random.PRNGKey(seed)
            self._base_key_seed = seed
        # optimizer rules take t starting at 1 (Adam bias correction —
        # step_fn derives t = prev_step + 1 on device)
        if first_call:
            import time as _time
            _ct0 = _time.perf_counter()
        new_params, new_buf, new_opt, new_step, loss, ok, mres = fn(
            st['params'], st['buffers'], st['opt'], self._base_key,
            jnp.asarray(st['step'], jnp.int32),
            jnp.asarray(self._optimizer.get_lr(), jnp.float32), *arrays)
        if first_call:
            # the first call of a new cache entry traces + XLA-compiles
            # synchronously before dispatching, so this delta IS the
            # compile cost (execution itself stays async)
            from .. import telemetry
            _dt = _time.perf_counter() - _ct0
            telemetry.event('compile', name='Model.train_batch',
                            dur_s=round(_dt, 6),
                            variants=len(self._train_step_cache))
            telemetry.add('compile.count')
            telemetry.add('compile.total_s', _dt)
        # donation invalidated the inputs — always adopt the returned
        # arrays (they hold the OLD values when the step was skipped)
        if self._check_finite_steps:
            # exact-skip contract: materialize ok (one host sync) so a
            # skipped step feeds no metrics and no optimizer tick
            ok = bool(ok)
            self._last_step_ok = ok
            st.update(params=new_params, buffers=new_buf, opt=new_opt,
                      step=st['step'] + (1 if ok else 0))
            self._optimizer._global_step = st['step']
            if not ok:
                # policy (strikes/rollback) is NanGuard's
                return loss, []
        else:
            # sync-free path: nothing here reads a device value — the
            # host runs ahead and keeps the XLA queue full.  `ok`
            # stays a device bool (NanGuard, if someone re-enables it,
            # pays the sync), the step counter advanced on device, and
            # mres was already masked to zero inside the module
            self._last_step_ok = ok
            st.update(params=new_params, buffers=new_buf, opt=new_opt,
                      step=new_step)
            self._optimizer._global_step = st['step']
        metric_logs = [m.update(r) if not isinstance(r, (tuple, list))
                       else m.update(*r)
                       for m, r in zip(self._metrics, mres)]
        return loss, metric_logs

    # -- fused K-step chunks (core.scan_loop) --------------------------------
    def train_chunk(self, stacked, n_in=None, k=None):
        """K compiled optimizer steps in ONE dispatch (whole-loop
        compilation, core.scan_loop): `stacked` is the chunk's batch —
        each array carries a leading K dim — and the call returns
        ``(losses, oks)`` as K-length DEVICE arrays.  The rng stream,
        skip contract and update math are bit-exact with K calls of
        :meth:`train_batch` (pinned by tests/test_fused_loop.py);
        what changes is cadence: ONE host round-trip per chunk, and
        under the default exact-skip posture ONE host sync per chunk
        (the finite-mask readback, ``scan_loop.chunk_sync``)."""
        assert self._optimizer is not None and self._loss is not None, \
            'call prepare(optimizer, loss) before train_chunk'
        import time as _time
        from ..core import scan_loop as _scan
        stacked = tuple(_to_jnp(v) for v in stacked)
        k = int(k if k is not None else stacked[0].shape[0])
        if n_in is None:
            _, n_in = self._split_batch([v[0] for v in stacked])
        st = self._get_fstate()
        key = self._batch_key(stacked, ('train-fused', n_in, k))
        first_call = key not in self._train_chunk_cache
        if first_call:
            if self._lint:
                self._lint_train_step(
                    n_in, st, [v[0] for v in stacked], fused=k)
            fused_fn = _scan.fused_hapi_step(
                self._build_train_step(n_in), k)
            jitted = jax.jit(fused_fn, donate_argnums=(0, 1, 2))
            from ..core import compile_cache as _cc
            if _cc.enabled():
                # the fused module rides the same persistent cache as
                # the per-step one; K folds into the fingerprint so
                # the two can never collide
                example = (st['params'], st['buffers'], st['opt'],
                           jax.random.PRNGKey(0),
                           jnp.zeros((), jnp.int32),
                           jnp.zeros((), jnp.float32), *stacked)
                fp = _cc.jaxpr_fingerprint(
                    'hapi-train-fused', fused_fn, example,
                    extra=('donate', (0, 1, 2), 'fused', k))
                jitted = _cc.through_cache(jitted, example, fp=fp,
                                           name='Model.train_chunk')
            from ..telemetry import memory as _mem
            if _mem.armed():
                _mem.maybe_note_compiled(
                    'Model.train_chunk', jitted,
                    (st['params'], st['buffers'], st['opt'],
                     jax.random.PRNGKey(0), jnp.zeros((), jnp.int32),
                     jnp.zeros((), jnp.float32), *stacked),
                    source='hapi')
            self._train_chunk_cache[key] = jitted
            from ..analysis import note_retrace
            note_retrace('Model.train_chunk',
                         len(self._train_chunk_cache), instance=self)
        fn = self._train_chunk_cache[key]
        from ..core import rng as rng_mod
        seed = rng_mod.get_seed()
        if getattr(self, '_base_key_seed', None) != seed:
            self._base_key = jax.random.PRNGKey(seed)
            self._base_key_seed = seed
        if first_call:
            _ct0 = _time.perf_counter()
        new_params, new_buf, new_opt, new_step, losses, oks, mres = fn(
            st['params'], st['buffers'], st['opt'], self._base_key,
            jnp.asarray(st['step'], jnp.int32),
            jnp.asarray(self._optimizer.get_lr(), jnp.float32),
            *stacked)
        if first_call:
            from .. import telemetry
            _dt = _time.perf_counter() - _ct0
            telemetry.event('compile', name='Model.train_chunk',
                            dur_s=round(_dt, 6), fused_steps=k,
                            variants=len(self._train_chunk_cache))
            telemetry.add('compile.count')
            telemetry.add('compile.total_s', _dt)
        if self._check_finite_steps:
            # exact-skip contract at chunk cadence: ONE sanctioned
            # host sync materializes the K-step finite mask; skipped
            # steps advanced neither the counter nor (on device) the
            # state.  NanGuard reads _last_step_ok once per chunk, so
            # the chunk reduces CONSERVATIVELY: any poisoned step
            # marks the whole chunk not-ok — a mostly-NaN chunk whose
            # last step happens finite must still count a strike
            # (strike granularity becomes per-chunk; see MIGRATION)
            mask = _scan.chunk_sync(oks)
            n_ok = int(mask.sum())
            self._last_step_ok = bool(mask.all())
            st.update(params=new_params, buffers=new_buf, opt=new_opt,
                      step=st['step'] + n_ok)
            self._optimizer._global_step = st['step']
        else:
            # sync-free path: zero host reads per chunk — the device
            # step counter is adopted lazily and the mask stays a
            # device array for whoever chooses to pay the sync
            self._last_step_ok = oks[-1]
            st.update(params=new_params, buffers=new_buf, opt=new_opt,
                      step=new_step)
            self._optimizer._global_step = st['step']
        self._chunk_metric_update(mres)
        return losses, oks

    @staticmethod
    def _merge_chunk_dim(v):
        """(K, N, ...) stacked metric stats -> (K*N, ...): metric
        update() accumulates sums/counts, so feeding the chunk-merged
        stats once equals feeding K per-step stats (skipped steps were
        already masked to zero on device)."""
        if getattr(v, 'ndim', 0) >= 2:
            return v.reshape((-1,) + tuple(v.shape[2:]))
        return v

    def _chunk_metric_update(self, mres):
        logs = []
        for m, r in zip(self._metrics, mres):
            if isinstance(r, (tuple, list)):
                logs.append(m.update(*[self._merge_chunk_dim(x)
                                       for x in r]))
            else:
                logs.append(m.update(self._merge_chunk_dim(r)))
        return logs

    def _lint_train_step(self, n_in, st, arrays, fused=None):
        """prepare(lint=...): audit the exact step about to compile
        (jaxpr rules, donation included) + the forward's source —
        via safe_emit, so only LintError (the 'error'-mode verdict)
        escapes and analyzer crashes degrade to a warning.

        Under an ACTIVE mesh (distributed env) the audit escalates to
        the lowered-HLO pass: the step is lowered in hapi's SPMD
        posture — state replicated, batch sharded over the mesh's
        first data axis — and the post-partitioner rules
        (replicated-giant-hlo, collective-cost, resharding,
        peak-memory) extend the jaxpr report."""
        from .. import analysis
        from ..distributed import env as _env

        def build():
            step_fn = self._build_train_step(n_in)
            args = (st['params'], st['buffers'], st['opt'],
                    jax.random.PRNGKey(0), jnp.zeros((), jnp.int32),
                    jnp.zeros((), jnp.float32))
            report = analysis.lint(
                step_fn, *args, *arrays,
                donate_argnums=(0, 1, 2), source=False,
                fused_steps=fused, name='Model.train_step')
            mesh = _env.get_mesh()
            if mesh is not None:
                analysis.escalate_hlo(
                    report, step_fn, args, arrays, mesh,
                    donate_argnums=(0, 1, 2), name='Model.train_step')
            return report.extend(analysis.lint_layer(self.network))

        analysis.safe_emit(build, self._lint)

    def _eval_batch_lazy(self, arrays, n_in):
        """One compiled eval step with NO host readback: the returned
        loss is a device array and metric updates are lazy jnp adds
        (SURVEY §2#21 — a sync per batch is a ~100 ms tunnel round
        trip on the real chip)."""
        st = self._get_fstate() if self._optimizer is not None else None
        if st is None:
            params, buffers = self.network.functional_state()
        else:
            params, buffers = st['params'], st['buffers']
        key = self._batch_key(arrays, ('eval', n_in))
        first_call = key not in self._eval_step_cache
        if first_call:
            self._eval_step_cache[key] = self._make_eval_step(n_in)
        # eval runs layers in eval() mode (dropout off), but seed from
        # the user's paddle.seed anyway: a layer that samples in eval
        # must not silently pin to a hard-coded stream
        from ..core import rng as rng_mod
        if first_call:
            import time as _time
            _ct0 = _time.perf_counter()
        outs, loss, mres = self._eval_step_cache[key](
            params, buffers, jax.random.PRNGKey(rng_mod.get_seed()),
            *arrays)
        if first_call:
            from .. import telemetry
            _dt = _time.perf_counter() - _ct0
            telemetry.event('compile', name='Model.eval_batch',
                            dur_s=round(_dt, 6),
                            variants=len(self._eval_step_cache))
            telemetry.add('compile.count')
            telemetry.add('compile.total_s', _dt)
        for m, r in zip(self._metrics, mres):
            m.update(r) if not isinstance(r, (tuple, list)) \
                else m.update(*r)
        return outs, loss

    def eval_batch(self, inputs, labels=None):
        """One compiled eval step; returns (loss, outputs) as DEVICE
        arrays — the old ``float(loss)`` / ``np.asarray(o)`` here cost
        a device→host round trip per batch (host-sync lint).  Call
        ``float(loss)`` / ``np.asarray(o)`` at your log boundary to
        materialize."""
        batch = _as_list(inputs) + _as_list(labels)
        arrays, n_in = self._split_batch(batch)
        outs, loss = self._eval_batch_lazy(arrays, n_in)
        return loss, list(outs)

    def predict_batch(self, inputs):
        arrays = [_to_jnp(b) for b in _as_list(inputs)]
        n_in = len(arrays)
        if self._fstate is not None:
            params, buffers = self._fstate['params'], \
                self._fstate['buffers']
        else:
            params, buffers = self.network.functional_state()
        key = self._batch_key(arrays, ('pred', n_in))
        if key not in self._pred_step_cache:
            self._pred_step_cache[key] = self._make_pred_step(n_in)
        from ..core import rng as rng_mod
        outs = self._pred_step_cache[key](
            params, buffers, jax.random.PRNGKey(rng_mod.get_seed()),
            *arrays)
        return [np.asarray(o) for o in outs]

    # -- loops ---------------------------------------------------------------
    def _to_loader(self, data, batch_size, shuffle, num_workers,
                   drop_last=False):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, profile=None, fused_steps=None):
        """``profile=`` enables sampled on-device trace capture over
        the train loop (telemetry.profile): None → the
        ``PADDLE_TPU_PROFILE`` env decides (default off), False forces
        off, True/str/dict/ProfileSchedule configure the windows.
        Trace artifacts land next to the flight-recorder dumps
        (``save_dir`` when given); each closed window emits a
        ``profile_capture`` event and the device-compute vs
        collective-time breakdown gauges.  Steps outside a window pay
        one integer compare — the sync-free loop contract holds.

        ``fused_steps=K`` compiles K train steps into ONE XLA module
        (core.scan_loop): batches are staged in K-step chunks
        (double-buffered device prefetch when ``num_workers>0``),
        losses/metrics accumulate on device inside the scan, and
        callbacks / logging / the preemption check run at chunk
        boundaries — dispatch overhead drops ~K-fold on small models.
        None defers to the ``PADDLE_TPU_FUSED_STEPS`` env (default
        off); K=1 is bit-exact with the per-step loop.  A short final
        chunk falls back to the per-step path."""
        assert self._optimizer is not None and self._loss is not None, \
            'call prepare(optimizer, loss) before fit'
        train_loader = self._to_loader(train_data, batch_size, shuffle,
                                       num_workers, drop_last=drop_last)
        eval_loader = self._to_loader(eval_data, batch_size, False,
                                      num_workers)
        steps = len(train_loader) if hasattr(train_loader, '__len__') \
            else None
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir,
            metrics=['loss'] + [m.name() for m in self._metrics])
        self.stop_training = False
        # preemption contract: SIGTERM during fit stops at the next
        # step boundary, ModelCheckpoint's on_train_end writes the
        # final checkpoint, and the tail of fit() exits
        # PREEMPTED_EXIT_CODE (SIGINT instead hands control back).
        # fit only BORROWS the handlers: if nothing else (launcher,
        # auto_checkpoint range) installed them, they are restored on
        # exit so a later Ctrl-C still kills the program normally
        from ..resilience import shutdown as _sd
        from .. import telemetry as _tel
        _owned_handlers = not _sd.handler_installed()
        _install_shutdown()
        try:
            with _tel.span('fit', epochs=epochs):
                self._fit_loop(cbks, train_loader, eval_loader, epochs,
                               eval_freq, batch_size, num_workers,
                               log_freq=log_freq, profile=profile,
                               save_dir=save_dir,
                               fused_steps=fused_steps)
        finally:
            requested = _sd.shutdown_requested()
            sig = _sd.preemption_signal()
            if _owned_handlers:
                _sd.uninstall_shutdown()
                if sig == _signal.SIGINT:
                    # user stop, and the latch is OURS: un-latch so
                    # the next fit starts fresh — on the exception
                    # path too, or a KeyboardInterrupt here would
                    # poison every later training loop.  A BORROWED
                    # latch is left set: the outer installer (e.g. an
                    # auto_checkpoint range wrapping this fit) still
                    # needs to see the request
                    _sd.clear_shutdown()
        if requested and sig != _signal.SIGINT:
            # preemption — SIGTERM or a programmatic request() from a
            # cluster agent: the final checkpoint just landed in
            # on_train_end, exit with the code the elastic supervisor
            # restarts for free.  SIGINT (user) instead returns
            # control with training cleanly stopped.  The flight
            # recorder lands NEXT TO that checkpoint so the preempted
            # worker is post-mortemable without live logs (the signal
            # handler already ring-buffered the preemption event; this
            # writes the durable copy inside the grace window).
            try:
                step = int(self._optimizer._global_step)
            except (TypeError, ValueError):
                step = -1
            _tel.event('preemption', signum=sig, where='hapi.fit',
                       step=step)
            dump_dir = save_dir or _tel.flight_dir()
            if dump_dir:
                _tel.dump_flight(os.path.join(
                    dump_dir, f'flightrec-{step}.json'))
            _sd.exit_if_requested()
        return self

    def _fit_loop(self, cbks, train_loader, eval_loader, epochs,
                  eval_freq, batch_size, num_workers, log_freq=10,
                  profile=None, save_dir=None, fused_steps=None):
        from .. import telemetry as _tel
        # sync-free telemetry: device loss scalars + host step/wait
        # times buffer in the accumulator and flush every
        # flush_interval steps (None when telemetry is not enabled)
        acc = _tel.step_accumulator('train')
        # sampled trace capture (telemetry.profile); None when off.
        # hapi steps carry no jit shardings, so windows yield the
        # profile_capture breakdown without the collective census
        # join — the mesh path (ParallelTrainer) does both.
        prof = _tel.step_profiler(profile, base_dir=save_dir,
                                  name='fit')
        # metric accumulate() is a device readback: pay it only on
        # steps some logger actually prints — the union of fit's
        # log_freq and every callback's own log_freq (a user
        # ProgBarLogger(log_freq=3) under fit(log_freq=10) must still
        # see metric values at ITS boundaries)
        log_freqs = {max(1, int(log_freq))}
        for cb in cbks:
            f = getattr(cb, 'log_freq', None)
            if isinstance(f, int) and f > 0:
                log_freqs.add(f)
        from ..core import scan_loop as _scan
        k = _scan.resolve_fused_steps(fused_steps)
        cbks.on_train_begin({})
        try:
            if k:
                self._fit_epochs_fused(
                    cbks, train_loader, eval_loader, epochs,
                    eval_freq, batch_size, num_workers, log_freqs,
                    acc, prof, k)
            else:
                self._fit_epochs(cbks, train_loader, eval_loader,
                                 epochs, eval_freq, batch_size,
                                 num_workers, log_freqs, acc, prof)
        finally:
            if prof is not None:
                # ALWAYS finalize — an exception mid-epoch must not
                # leave jax.profiler tracing for the rest of the
                # process (every later window would fail to start).
                # sync on the last loss so a still-open window waits
                # for its traced async steps before stop_trace.
                prof.close(sync=self._last_fit_loss)

    def _fit_epochs(self, cbks, train_loader, eval_loader, epochs,
                    eval_freq, batch_size, num_workers, log_freqs,
                    acc, prof):
        import time as _time
        _perf = _time.perf_counter
        gstep = 0
        self._last_fit_loss = None
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch, {})
            for m in self._metrics:
                m.reset()
            logs = {}
            step = -1
            loader_it = iter(train_loader)
            while True:
                _tw0 = _perf()
                try:
                    batch = next(loader_it)
                except StopIteration:
                    break
                wait_s = _perf() - _tw0
                step += 1
                cbks.on_train_batch_begin(step, {})
                arrays, n_in = self._split_batch(batch)
                _ts0 = _perf()
                loss, _ = self.train_batch(arrays[:n_in], arrays[n_in:])
                self._last_fit_loss = loss
                if acc is not None:
                    acc.observe(step=step, step_time_s=_perf() - _ts0,
                                wait_s=wait_s, loss=loss)
                if prof is not None:
                    prof.observe(gstep, sync=loss)   # 0-based index
                gstep += 1
                logs = {'loss': loss}
                if any((step + 1) % f == 0 for f in log_freqs):
                    for m in self._metrics:
                        logs[str(m.name())] = m.accumulate()
                cbks.on_train_batch_end(step, logs)
                if _shutdown_requested():
                    # preemption (SIGTERM latched by GracefulShutdown):
                    # stop at this step boundary; on_train_end below
                    # runs ModelCheckpoint's final save — the "final
                    # synchronous checkpoint" of the preemption
                    # contract — and the caller's exit_if_requested()
                    # turns it into PREEMPTED_EXIT_CODE
                    self.stop_training = True
                if self.stop_training:
                    break
            if acc is not None:
                acc.flush()
            for m in self._metrics:
                logs[str(m.name())] = m.accumulate()
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training:
                # preemption/early-stop: every second of the grace
                # window belongs to the final checkpoint, not to an
                # eval pass
                break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(
                    eval_loader, batch_size=batch_size, verbose=0,
                    num_workers=num_workers, _callbacks=cbks)
                cbks.on_eval_end(eval_logs)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        self._sync_back()

    def _fit_epochs_fused(self, cbks, train_loader, eval_loader,
                          epochs, eval_freq, batch_size, num_workers,
                          log_freqs, acc, prof, k):
        """The K-step fused epoch loop (core.scan_loop): batches are
        staged in K-chunks — stacked + device-put on a background
        thread when the loader has workers, so chunk N+1's transfer
        overlaps chunk N's execution — and each chunk is ONE compiled
        dispatch.  Callbacks, logging and the preemption check run at
        chunk boundaries; a short final chunk takes the per-step
        path.  Losses stay device arrays throughout (the
        accumulator's chunk rows expand to per-step stats at flush)."""
        import time as _time
        from ..core import scan_loop as _scan
        from .. import telemetry as _tel
        _perf = _time.perf_counter
        gstep = 0
        self._last_fit_loss = None

        def stage(batches):
            # keep leaves RAW (numpy stays host, Tensors unwrap to
            # their device values): stack_batches then pays one
            # transfer per host field and zero readbacks for device
            # fields — no _to_jnp round-trip before stacking
            rows = [[v.value if isinstance(v, Tensor) else v
                     for v in _as_list(b)] for b in batches]
            return (_scan.stack_batches(rows),
                    self._split_arity(len(rows[0])))

        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch, {})
            for m in self._metrics:
                m.reset()
            logs = {}
            step = -1
            # overlap decision follows the LOADER's own workers (a
            # pre-built DataLoader(num_workers=4) must get background
            # staging even when fit's num_workers default is 0)
            loader_workers = getattr(train_loader, 'num_workers',
                                     None)
            if loader_workers is None:
                loader_workers = num_workers
            pref = _scan.ChunkPrefetcher(
                iter(train_loader), k, stage,
                background=loader_workers > 0)
            for staged, n, wait_s in pref:
                if n == k:
                    (stacked, n_in) = staged
                    cbks.on_train_batch_begin(step + 1, {})
                    _ts0 = _perf()
                    losses, _oks = self.train_chunk(stacked, n_in, k)
                    dt = _perf() - _ts0
                    loss = losses[-1]
                    self._last_fit_loss = loss
                    if acc is not None:
                        acc.observe_chunk(step + 1, n, step_time_s=dt,
                                          wait_s=wait_s, loss=losses)
                    _tel.set_gauge('fused.host_wait_ms',
                                   round(wait_s * 1000.0, 4))
                    if prof is not None:
                        prof.observe(gstep, sync=loss, span=n)
                    gstep += n
                    step += n
                    logs = {'loss': loss}
                    if any((step + 1 - j) % f == 0
                           for f in log_freqs for j in range(n)):
                        for m in self._metrics:
                            logs[str(m.name())] = m.accumulate()
                    cbks.on_train_batch_end(step, logs)
                else:
                    # ragged tail: run the < K remaining batches
                    # through the per-step module instead of paying a
                    # one-off K'-length compile
                    for batch in staged:
                        step += 1
                        cbks.on_train_batch_begin(step, {})
                        arrays, n_in = self._split_batch(batch)
                        _ts0 = _perf()
                        loss, _ = self.train_batch(arrays[:n_in],
                                                   arrays[n_in:])
                        self._last_fit_loss = loss
                        if acc is not None:
                            acc.observe(step=step,
                                        step_time_s=_perf() - _ts0,
                                        loss=loss)
                        if prof is not None:
                            prof.observe(gstep, sync=loss)
                        gstep += 1
                        logs = {'loss': loss}
                        if any((step + 1) % f == 0 for f in log_freqs):
                            for m in self._metrics:
                                logs[str(m.name())] = m.accumulate()
                        cbks.on_train_batch_end(step, logs)
                if _shutdown_requested():
                    # preemption lands at the chunk boundary we are on:
                    # fused granularity is K steps, and the state here
                    # IS a chunk boundary — the final checkpoint in
                    # on_train_end restores to exactly this step
                    self.stop_training = True
                if self.stop_training:
                    break
            if acc is not None:
                acc.flush()
            for m in self._metrics:
                logs[str(m.name())] = m.accumulate()
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(
                    eval_loader, batch_size=batch_size, verbose=0,
                    num_workers=num_workers, _callbacks=cbks)
                cbks.on_eval_end(eval_logs)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        self._sync_back()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _callbacks=None):
        loader = self._to_loader(eval_data, batch_size, False, num_workers)
        for m in self._metrics:
            m.reset()
        total_loss, n_batches = 0.0, 0
        cbks = _callbacks
        if cbks is None:
            cbks = config_callbacks(
                callbacks, model=self, batch_size=batch_size,
                steps=len(loader) if hasattr(loader, '__len__') else None,
                log_freq=log_freq, verbose=verbose, mode='eval',
                metrics=['loss'] + [m.name() for m in self._metrics])
            cbks.on_eval_begin({})
        from .. import telemetry as _tel
        with _tel.span('evaluate'):
            for step, batch in enumerate(loader):
                arrays, n_in = self._split_batch(batch)
                # lazy path: the loss stays a device array and the
                # metric updates are jnp adds — zero per-batch host
                # syncs; a callback that formats the loss pays the
                # sync itself, and only when it actually logs
                _, loss = self._eval_batch_lazy(arrays, n_in)
                total_loss = total_loss + loss
                n_batches += 1
                cbks.on_eval_batch_end(step, {'loss': loss})
        logs = {'loss': float(total_loss) / max(1, n_batches)}
        for m in self._metrics:
            logs[str(m.name())] = m.accumulate()
        if _callbacks is None:
            cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = self._to_loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            arrays, n_in = self._split_batch(batch)
            outs = self.predict_batch(arrays[:n_in])
            outputs.append(outs)
        # transpose: list-of-batches -> per-output lists
        n_out = len(outputs[0]) if outputs else 0
        per_out = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            per_out = [np.concatenate(o, axis=0) for o in per_out]
        return per_out

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        self._sync_back()
        if training:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            _save(self.network.state_dict(), path + '.pdparams')
            if self._optimizer is not None:
                _save(self._optimizer.state_dict(), path + '.pdopt')
        else:
            from .. import jit as _jit
            _jit.save(self.network, path)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        sd = _load(path + '.pdparams')
        try:
            self.network.set_state_dict(sd)
        except (KeyError, ValueError):
            if not skip_mismatch:
                raise
            warnings.warn('skip_mismatch=True: partially loaded')
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + '.pdopt'):
            self._optimizer.set_state_dict(_load(path + '.pdopt'))
        self._invalidate()
        return self

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
