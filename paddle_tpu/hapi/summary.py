"""Model summary (reference: python/paddle/hapi/model_summary.py).

Walks the Layer tree with forward hooks on a dummy forward, reporting
per-layer output shapes and parameter counts.  Runs eager on host-sized
dummy inputs; no TPU compile is triggered beyond the ops themselves.
"""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import Layer

__all__ = ['summary']


def _num_params(layer):
    own = [p for _, p in layer.named_parameters(include_sublayers=False)]
    return sum(int(np.prod(p.shape)) for p in own), \
        sum(int(np.prod(p.shape)) for p in own if not p.stop_gradient)


def _shape_of(out):
    if isinstance(out, Tensor):
        return list(out.shape)
    if isinstance(out, (list, tuple)) and out:
        return _shape_of(out[0])
    return []


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params', 'trainable_params'}."""
    assert isinstance(net, Layer)
    if input is None:
        assert input_size is not None, 'need input_size or input'
        sizes = input_size if isinstance(input_size, list) and \
            isinstance(input_size[0], (list, tuple)) else [input_size]
        dtypes = dtypes or ['float32'] * len(sizes)
        if isinstance(dtypes, str):
            dtypes = [dtypes] * len(sizes)
        inputs = [Tensor(jnp.zeros([s if s is not None else 1
                                    for s in size], dtype=dt))
                  for size, dt in zip(sizes, dtypes)]
    else:
        inputs = input if isinstance(input, (list, tuple)) else [input]

    rows = []
    hooks = []

    def register(layer, prefix):
        subs = list(layer._sub_layers.items())
        if not subs:
            def hook(l, inp, out, name=prefix,
                     cls=layer.__class__.__name__):
                tot, train = _num_params(l)
                rows.append((f'{cls}-{len(rows) + 1}', name,
                             _shape_of(out), tot))
            hooks.append(layer.register_forward_post_hook(hook))
        for name, sub in subs:
            register(sub, f'{prefix}.{name}' if prefix else name)

    register(net, '')
    was_training = net.training
    net.eval()
    try:
        net(*inputs)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    name_w = max([len(r[0]) for r in rows] + [12]) + 2
    shape_w = max([len(str(r[2])) for r in rows] + [14]) + 2
    line = '-' * (name_w + shape_w + 14)
    print(line)
    print(f"{'Layer (type)':<{name_w}}{'Output Shape':<{shape_w}}"
          f"{'Param #':>12}")
    print('=' * (name_w + shape_w + 14))
    for cls_name, _, shape, n in rows:
        print(f'{cls_name:<{name_w}}{str(shape):<{shape_w}}{n:>12,}')
    print('=' * (name_w + shape_w + 14))
    print(f'Total params: {total:,}')
    print(f'Trainable params: {trainable:,}')
    print(f'Non-trainable params: {total - trainable:,}')
    print(line)
    return {'total_params': total, 'trainable_params': trainable}
