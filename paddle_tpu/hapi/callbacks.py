"""Training callbacks.

Reference analogue: python/paddle/hapi/callbacks.py (Callback,
ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping, VisualDL).
VisualDL has no TPU-side service here, so it degrades to a JSONL event
log with the same constructor.
"""
import numbers
import os
import sys
import time

from ..resilience import NanSentinel

__all__ = ['Callback', 'ProgBarLogger', 'ModelCheckpoint', 'LRScheduler',
           'EarlyStopping', 'VisualDL', 'ReduceLROnPlateau', 'NanGuard',
           'config_callbacks']


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def append(self, cb):
        self.callbacks.append(cb)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def __getattr__(self, name):
        if name.startswith('on_'):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


def _fmt(v):
    if isinstance(v, numbers.Number):
        return '{:.4f}'.format(v)
    if isinstance(v, (list, tuple)):
        return '[' + ', '.join(_fmt(x) for x in v) + ']'
    # lazy eval path: logs carry DEVICE scalars (jnp arrays / Tensors)
    # so the host sync happens here, only when a logger actually
    # prints — float() them for the same formatting as plain numbers
    try:
        return '{:.4f}'.format(float(getattr(v, 'value', v)))
    except (TypeError, ValueError):
        return str(v)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.epochs = None
        self.steps = None

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get('epochs')
        self._t0 = time.time()

    def on_eval_begin(self, logs=None):
        self.steps = self.params.get('steps')

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get('steps')
        self._epoch_t0 = time.time()
        if self.verbose and self.epochs:
            print('Epoch {}/{}'.format(epoch + 1, self.epochs))

    def _print_logs(self, prefix, step, logs):
        logs = logs or {}
        items = ['{}: {}'.format(k, _fmt(v)) for k, v in logs.items()]
        total = self.steps if self.steps else '?'
        print('{} step {}/{} - {}'.format(
            prefix, step + 1, total, ' - '.join(items)), file=sys.stderr
            if self.verbose == 1 else sys.stdout)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose == 2 and (step + 1) % self.log_freq == 0:
            self._print_logs('train', step, logs)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._epoch_t0
            items = ['{}: {}'.format(k, _fmt(v))
                     for k, v in (logs or {}).items()]
            print('Epoch {} done in {:.1f}s - {}'.format(
                epoch + 1, dt, ' - '.join(items)))

    def on_eval_batch_end(self, step, logs=None):
        if self.verbose == 2 and (step + 1) % self.log_freq == 0:
            self._print_logs('eval', step, logs)

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = ['{}: {}'.format(k, _fmt(v))
                     for k, v in (logs or {}).items()]
            print('Eval - ' + ' - '.join(items))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, 'final'))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step or by_epoch)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step ^ by_epoch, 'exactly one of by_step/by_epoch'
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, '_optimizer', None)
        lr = getattr(opt, '_learning_rate', None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor='loss', mode='auto', patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == 'min' or (mode == 'auto' and 'acc' not in monitor):
            self.is_better = lambda cur, best: cur < best - self.min_delta
            self.best = float('inf')
        else:
            self.is_better = lambda cur, best: cur > best + self.min_delta
            self.best = -float('inf')

    def on_train_begin(self, logs=None):
        self.wait = 0
        if self.baseline is not None:
            self.best = self.baseline

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.is_better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and \
                    self.params.get('save_dir') is not None:
                self.model.save(os.path.join(self.params['save_dir'],
                                             'best_model'))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print('Early stopping: {} did not improve beyond '
                          '{:.5f}'.format(self.monitor, self.best))


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor='loss', factor=0.1, patience=10, verbose=1,
                 mode='auto', min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == 'min' or (mode == 'auto' and 'acc' not in monitor):
            self.is_better = lambda cur, best: cur < best - self.min_delta
            self.best = float('inf')
        else:
            self.is_better = lambda cur, best: cur > best + self.min_delta
            self.best = -float('inf')
        self.wait = 0
        self.cooldown_counter = 0

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.is_better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                opt = self.model._optimizer
                new_lr = max(opt.get_lr() * self.factor, self.min_lr)
                opt.set_lr(new_lr)
                if self.verbose:
                    print('ReduceLROnPlateau: lr -> {:.6g}'.format(new_lr))
                self.cooldown_counter = self.cooldown
                self.wait = 0


class NanGuard(Callback):
    """Divergence sentinel for Model.fit (resilience.NanSentinel
    policy).  The compiled train step already SKIPS a non-finite
    update on device (old params kept — see Model._make_train_step);
    this callback adds the host-side policy: count consecutive
    skipped steps, and after `patience` strikes roll the model back
    to the last known-good state (captured at train begin and after
    every clean epoch — the same boundaries ModelCheckpoint persists
    to disk).  After `max_rollbacks` rollbacks the run raises
    FloatingPointError instead of looping on a poisoned setup.

    MEMORY: the rollback snapshot is a full device-side copy of
    params + optimizer state + buffers, held for the whole fit — fine
    for the models hapi targets, but a workload already at capacity
    should pass NanGuard(rollback=False) (skip-only: non-finite
    updates are still dropped on device at zero extra memory, there
    is just nothing to roll back to) or NanGuard(enable=False).  At
    1.3B scale use ParallelTrainer(nan_guard=True), which rolls back
    to its on-disk committed checkpoint instead of a live copy.

    Added to fit() by default; pass your own instance to tune.
    """

    def __init__(self, patience=3, max_rollbacks=2, enable=True,
                 rollback=True, verbose=1):
        super().__init__()
        self.enable = enable
        self.rollback = rollback
        self.verbose = verbose
        self.sentinel = NanSentinel(patience=patience,
                                    max_rollbacks=max_rollbacks)
        self._epoch_skip_base = 0

    def on_train_begin(self, logs=None):
        # the guard is the only default consumer of the per-step
        # finiteness flag: disabling it flips Model.train_batch onto
        # the sync-free path (loss/ok stay device arrays, step counter
        # advances on device) — the host-sync-free posture for
        # throughput runs
        self.model._check_finite_steps = bool(self.enable)
        if self.enable and self.rollback:
            self.model._capture_good_state()

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch_skip_base = self.sentinel.total_skipped

    def on_train_batch_end(self, step, logs=None):
        if not self.enable:
            return
        action = self.sentinel.observe(
            finite=getattr(self.model, '_last_step_ok', True))
        if action == 'skip' and self.verbose:
            print('NanGuard: non-finite loss/grad at step {} — update '
                  'skipped ({}/{} strikes)'.format(
                      step + 1, self.sentinel.strikes,
                      self.sentinel.patience), file=sys.stderr)
        elif action == 'rollback':
            rolled = self.rollback and \
                self.model._rollback_to_good_state()
            # post-mortem evidence: the sentinel already emitted the
            # nan_rollback event; write the durable flight-recorder
            # copy next to the checkpoints when a save_dir exists
            if self.params.get('save_dir'):
                from ..telemetry import dump_flight
                dump_flight(os.path.join(
                    self.params['save_dir'],
                    f'flightrec-{step + 1}.json'))
            if self.verbose:
                print('NanGuard: {} consecutive non-finite steps — '
                      '{}'.format(
                          self.sentinel.patience,
                          'rolled back to last good state' if rolled
                          else 'no snapshot to roll back to; '
                               'continuing with skipped updates'),
                      file=sys.stderr)

    def on_epoch_end(self, epoch, logs=None):
        # refresh the rollback target only after a CLEAN epoch — an
        # epoch containing skips may already carry subtly-poisoned
        # state even though every applied update was finite
        if self.enable and self.rollback and \
                self.sentinel.total_skipped == self._epoch_skip_base:
            self.model._capture_good_state()


class VisualDL(Callback):
    """Scalar logging onto the telemetry ScalarAdapter (no VisualDL
    service on TPU hosts — same constructor as the reference's
    VisualDL callback; same ``events.jsonl`` on disk, and every record
    additionally lands in the telemetry stream as a ``scalar`` event).

    Sync-free by buffering: logs carry DEVICE scalars on the lazy
    train path, and the old per-step ``float(loss)`` write stalled the
    XLA queue every batch — the exact host sync the sync-free loop
    removed.  Records now buffer un-materialized and are floated +
    written only every `log_freq` steps and at epoch/eval/train end;
    by then the buffered arrays are log_freq steps old and already
    computed, so the flush does not stall the current step."""

    def __init__(self, log_dir='./log', log_freq=10):
        super().__init__()
        self.log_dir = log_dir
        self.log_freq = max(1, int(log_freq))
        self._writer = None
        self._step = 0
        self._buf = []      # (tag, step, {key: device-or-py scalar})

    def _adapter(self):
        if self._writer is None:
            from ..telemetry import ScalarAdapter
            self._writer = ScalarAdapter(self.log_dir)
        return self._writer

    @staticmethod
    def _materialize(v):
        if isinstance(v, numbers.Number):
            return v
        if isinstance(v, (list, tuple)) and v and \
                isinstance(v[0], numbers.Number):
            return list(v)
        try:
            return float(getattr(v, 'value', v))
        except (TypeError, ValueError):
            return None

    def flush(self):
        """Materialize buffered device scalars (the one sync, at the
        log boundary) and write them through the adapter."""
        if not self._buf:
            return
        buf, self._buf = self._buf, []
        w = self._adapter()
        for tag, step, logs in buf:
            vals = {}
            for k, v in logs.items():
                fv = self._materialize(v)
                if fv is not None:
                    vals[k] = fv
            w.write_record(tag, step, vals)

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._buf.append(('train', self._step, dict(logs or {})))
        if len(self._buf) >= self.log_freq:
            self.flush()

    def on_epoch_end(self, epoch, logs=None):
        self.flush()

    def on_eval_end(self, logs=None):
        self._buf.append(('eval', self._step, dict(logs or {})))
        self.flush()

    def on_train_end(self, logs=None):
        self.flush()
        if self._writer is not None:
            self._writer.close()
            self._writer = None


def config_callbacks(callbacks=None, model=None, batch_size=None,
                     epochs=None, steps=None, log_freq=2, verbose=2,
                     save_freq=1, save_dir=None, metrics=None, mode='train'):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if mode == 'train' and not any(isinstance(c, NanGuard) for c in cbks):
        cbks.append(NanGuard())
    cb_list = CallbackList(cbks)
    cb_list.set_model(model)
    cb_list.set_params({
        'batch_size': batch_size, 'epochs': epochs, 'steps': steps,
        'verbose': verbose, 'metrics': metrics or [], 'save_dir': save_dir})
    return cb_list
