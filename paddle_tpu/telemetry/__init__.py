"""paddle_tpu.telemetry — unified, sync-free run telemetry.

A run's health used to be scattered: profiler HLO tables, lint
warnings, resilience log lines, per-callback progress printing.  This
package is the one structured record of *what happened during a run*:

* **spans** — ``with telemetry.span('compile'):`` nested
  monotonic-clock timers (compile, checkpoint_save/restore, fit,
  evaluate), aggregated per name and streamed as events;
* **counters / gauges** — retrace counts, dataloader host-wait
  seconds, collective bytes;
* **typed events** — ``compile``, ``retrace``,
  ``checkpoint_save/commit/restore/quarantine``, ``preemption``,
  ``nan_skip/rollback/fatal``, ``lint_finding``, ``steps`` (flushed
  per-step scalars), emitted by hapi / parallel / jit / resilience /
  analysis / io at their natural boundaries;
* a **flight recorder** — the bounded ring of the last N events that
  resilience dumps to ``<ckpt_dir>/flightrec-<step>.json`` on SIGTERM
  preemption, NaN rollback, or crash, so a preempted TPU worker is
  post-mortemable without live logs;
* **exporters** — a rank-tagged JSONL stream per host
  (``telemetry-r<rank>.jsonl``) merged by ``tools/run_report.py``
  into step-time percentiles, compile totals, retrace counts, the
  device-step vs host-wait split, and the resilience event timeline.

The contract that makes this safe to leave on: **the step path is
sync-free**.  Per-step scalars (loss, tokens) are buffered as DEVICE
arrays by ``StepAccumulator`` and read back only every
``flush_interval`` steps (default 32) — by then they are long
computed, so the flush never stalls the XLA queue.  Everything else
emits at boundary rate (compile / checkpoint / epoch), never per step.
``tests/test_event_telemetry.py`` pins this with a device→host
transfer guard and the ``analysis`` host-sync rule.

Usage::

    from paddle_tpu import telemetry
    telemetry.enable('/ckpt/run7/telemetry')     # JSONL + step stats
    ...train...                                  # emission is wired in
    telemetry.dump_flight('/ckpt/run7/flightrec-manual.json')

    $ python tools/run_report.py /ckpt/run7/telemetry

Hard kill switch: ``PADDLE_TPU_TELEMETRY=0`` (every entry point
no-ops).  Without ``enable()`` the recorder still keeps the in-memory
flight ring + counters (boundary-rate, negligible) so crash/preemption
dumps work out of the box; ``enable()`` adds the JSONL stream and the
per-step accumulation.
"""
import contextlib
import os
import sys

from .recorder import (  # noqa: F401
    Recorder, get_recorder, reset, hard_off, EVENT_KINDS)
from .stepstats import (  # noqa: F401
    StepAccumulator, StepTimer, percentiles)
from .exporters import (  # noqa: F401
    JsonlWriter, ScalarAdapter, TensorBoardWriter, TeeWriter)
from .profile import (  # noqa: F401
    ProfileSchedule, StepProfiler, step_profiler, capture,
    resolve_schedule)
from .live import (  # noqa: F401
    LiveAggregator, RollingWindow, RateCounter)
from .monitors import (  # noqa: F401
    SLOMonitor, DriftMonitor, MemoryMonitor)
from .memory import (  # noqa: F401
    MemConfig, MemorySampler, resolve_memstats, note_compiled,
    maybe_note_compiled, ensure_sampler, stop_sampler)
from .httpd import (  # noqa: F401
    MetricsServer, resolve_metrics_port, attach_source)
from .cluster import (  # noqa: F401
    ClusterPublisher, ClusterAggregator, ClusterPlane,
    enable_cluster_plane, resolve_cluster_stats)

__all__ = [
    'Recorder', 'get_recorder', 'reset', 'hard_off', 'EVENT_KINDS',
    'StepAccumulator', 'StepTimer', 'percentiles',
    'JsonlWriter', 'ScalarAdapter', 'TensorBoardWriter', 'TeeWriter',
    'ProfileSchedule', 'StepProfiler', 'step_profiler', 'capture',
    'resolve_schedule',
    'LiveAggregator', 'RollingWindow', 'RateCounter',
    'SLOMonitor', 'DriftMonitor', 'MemoryMonitor',
    'MemConfig', 'MemorySampler', 'resolve_memstats', 'note_compiled',
    'maybe_note_compiled', 'ensure_sampler', 'stop_sampler',
    'MetricsServer', 'resolve_metrics_port', 'attach_source',
    'ClusterPublisher', 'ClusterAggregator', 'ClusterPlane',
    'enable_cluster_plane', 'resolve_cluster_stats',
    'enable', 'disable', 'enabled', 'active',
    'event', 'add', 'set_gauge', 'span', 'events',
    'step_accumulator', 'dump_flight', 'flight_dir',
]

_enabled = False
_prev_excepthook = None
_crash_dir = None


def active():
    """True when telemetry records at all (the default; in-memory
    flight ring + counters).  False only under PADDLE_TPU_TELEMETRY=0."""
    return not hard_off()


def enabled():
    """True when enable() turned on the JSONL export + per-step
    accumulation (the opt-in, heavier-weight layer)."""
    return _enabled and not hard_off()


def enable(log_dir=None, flush_interval=32, crash_dump=True,
           max_events=None, tensorboard=False):
    """Turn on full telemetry: stream events to
    ``<log_dir>/telemetry-r<rank>.jsonl``, activate the sync-free
    per-step accumulators in hapi/ParallelTrainer at
    ``flush_interval``, and (default) install a crash hook that dumps
    the flight recorder on an unhandled exception.

    log_dir=None keeps everything in memory (step accumulation and
    flight dumps still work; nothing streams to disk).

    tensorboard=True additionally writes TensorBoard-native event
    files (``events.out.tfevents.*``) next to the JSONL: the SAME
    buffered device scalars — ``steps`` flushes and ``scalar``
    records — become TB scalar points at their flush boundary, so the
    export adds zero per-step host syncs (stdlib-only writer, see
    exporters.TensorBoardWriter)."""
    global _enabled, _crash_dir
    if hard_off():
        return None
    rec = get_recorder()
    if max_events is not None:
        # resize the ring in place, keeping the newest events
        from collections import deque
        rec._events = deque(rec._events, maxlen=max_events)
    rec.flush_interval = max(1, int(flush_interval))
    if log_dir is not None:
        writer = JsonlWriter(log_dir)
        if tensorboard:
            writer = TeeWriter(writer, TensorBoardWriter(log_dir))
        old = rec.attach_writer(writer)
        if old is not None:
            old.close()
        _crash_dir = os.path.abspath(log_dir)
    _enabled = True
    if crash_dump:
        _install_crash_hook()
    meta = {'pid': os.getpid(), 'argv': list(sys.argv),
            'flush_interval': rec.flush_interval}
    try:
        import jax
        meta['backend'] = jax.default_backend()
        meta['process_count'] = jax.process_count()
    except Exception:
        pass
    rec.event('run_meta', **meta)
    return rec


def disable():
    """Detach the JSONL writer and stop per-step accumulation; the
    in-memory flight ring keeps recording (see active())."""
    global _enabled
    _enabled = False
    rec = get_recorder()
    w = rec.attach_writer(None)
    if w is not None:
        w.close()
    _remove_crash_hook()


def flight_dir():
    """The directory crash dumps land in (the enable() log_dir), or
    None — call sites with a better home (a checkpoint dir) pass their
    own path to dump_flight()."""
    return _crash_dir


# -- module-level conveniences (the emission API call sites use) --------------

def event(kind, **data):
    if hard_off():
        return None
    return get_recorder().event(kind, **data)


def add(name, n=1):
    if hard_off():
        return
    get_recorder().add(name, n)


def set_gauge(name, value):
    if hard_off():
        return
    get_recorder().set_gauge(name, value)


def events(kind=None):
    return get_recorder().events(kind)


def span(name, **attrs):
    """``with telemetry.span('compile'): ...`` — no-op under the hard
    kill switch."""
    if hard_off():
        return contextlib.nullcontext()
    return get_recorder().span(name, **attrs)


def step_accumulator(tag='train', flush_interval=None):
    """A StepAccumulator for a step loop, or None when full telemetry
    is off — loops guard with ``if acc is not None``."""
    if not enabled():
        return None
    return StepAccumulator(tag=tag, flush_interval=flush_interval)


def dump_flight(path):
    """Write the flight-recorder JSON to `path` (atomic; never
    raises).  Returns the path or None."""
    if hard_off():
        return None
    return get_recorder().dump_flight(path)


# -- crash hook ---------------------------------------------------------------

def _crash_hook(exc_type, exc, tb):
    try:
        d = _crash_dir or '.'
        from .recorder import _rank
        get_recorder().event_unlocked(
            'crash', error=repr(exc)[:300],
            exc_type=getattr(exc_type, '__name__', str(exc_type)))
        get_recorder().dump_flight(
            os.path.join(d, f'flightrec-crash-r{_rank()}.json'))
    except Exception:
        pass
    (_prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)


def _install_crash_hook():
    global _prev_excepthook
    if sys.excepthook is _crash_hook:
        return
    _prev_excepthook = sys.excepthook
    sys.excepthook = _crash_hook


def _remove_crash_hook():
    global _prev_excepthook
    if sys.excepthook is _crash_hook:
        sys.excepthook = _prev_excepthook or sys.__excepthook__
        _prev_excepthook = None
