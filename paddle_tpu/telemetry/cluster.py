"""Cluster observability plane: live multi-host TRAINING telemetry.

PR 13 made the *serving* runtime observable while it runs; the
multi-host training cluster stayed post-hoc — per-rank JSONL merged by
``tools/run_report.py`` after the job exits, which is exactly when
straggler and divergence information stops being actionable.  This
module is the training-side sensor substrate (ROADMAP item 3):

* :class:`ClusterPublisher` — runs on EVERY rank.  Subscribes to the
  process Recorder's boundary-rate stream (``Recorder.subscribe`` —
  the same buffered ``steps`` flushes the exporters consume, so zero
  new device syncs and nothing per-step) and periodically overwrites
  one compact **stats frame** on the existing
  ``distributed.collective`` KV transport: rolling step-time
  percentiles, last step / last committed step, compile + retrace
  counts, predicted-vs-observed collective ratio, a loss-window
  digest, and the rolling means of any extra per-step columns the
  loop feeds its accumulator (e.g. the soak worker's
  ``compute_ms``/``coll_ms`` split).  Publishing is a non-blocking
  KV overwrite (``HostCollectives.post_stats``) — a publisher can
  never stall or kill a step.
* :class:`ClusterAggregator` — runs on rank 0 (or any observer).
  ``collect()`` non-blockingly reads every rank's latest frame plus
  the watchdog heartbeats and joins them into ONE cluster view:

  - per-rank step-time **skew** with straggler *attribution* (which
    rank, how far behind, stale heartbeat or stale frame), via
    :func:`attribute_straggler`;
  - a per-step **critical-path breakdown** — compute vs collective
    vs host-wait vs slowest-rank wait — when frames carry the
    compute/collective split;
  - a cross-rank **loss-divergence** digest (relative spread of the
    per-rank loss windows);
  - **degraded-view semantics**: a dead or wedged rank's frame goes
    stale and is *marked* stale (age, last step, heartbeat age) —
    the view degrades, it never crashes.  Chaos-validated by
    ``bench.py --cluster-obs-smoke`` (SIGKILL mid-run).

  The view is served through the PR-13 ``MetricsServer`` as
  ``/cluster/status.json`` + ``/metrics`` families
  (``MetricsServer.add_source`` — one port, serving AND cluster
  views), and attached ``telemetry.monitors`` latch typed
  ``straggler_suspect`` / ``rank_divergence`` events off it — the
  edges a future ``plan_supervisor`` consumes.

Default OFF everywhere: arm with ``ParallelTrainer(cluster_stats=…)``
or ``PADDLE_TPU_CLUSTER_STATS=1`` (off/0/unset = off; a float value
sets the publish interval in seconds).
"""
import json
import os
import threading
import time

from .live import RollingWindow
from .recorder import get_recorder

__all__ = ['ClusterPublisher', 'ClusterAggregator', 'ClusterPlane',
           'attribute_straggler', 'critical_path', 'loss_divergence',
           'resolve_cluster_stats', 'enable_cluster_plane',
           'CLUSTER_STATS_ENV', 'FRAME_VERSION']

CLUSTER_STATS_ENV = 'PADDLE_TPU_CLUSTER_STATS'
FRAME_VERSION = 1

_MONO = time.monotonic
_WALL = time.time


def resolve_cluster_stats(arg=None):
    """The shared opt-in posture (mirrors ``resolve_watchdog`` /
    ``resolve_metrics_port``): explicit ``False`` -> None (off even if
    the env says on); ``True`` -> default interval; a number -> that
    publish interval in seconds; ``None`` -> the
    PADDLE_TPU_CLUSTER_STATS env decides (unset/'0'/'off'/'false' =
    off, '1'/'on' = default, a float = interval).  Returns the publish
    interval in seconds, or None for off."""
    if arg is False:
        return None
    if arg is True:
        return 2.0
    if arg is not None:
        return float(arg)
    text = (os.environ.get(CLUSTER_STATS_ENV) or '').strip().lower()
    if text in ('', '0', 'off', 'false'):
        return None
    if text in ('1', 'on', 'true'):
        return 2.0
    try:
        return float(text)
    except ValueError:
        return None


def _median(vals):
    """Proper even-count median (a 2-rank cluster must not anchor a
    baseline on the slower rank).  None for an empty input.
    tools/run_report.py carries its own copy on purpose: it must run
    stdlib-only on a machine with no paddle_tpu install."""
    if not vals:
        return None
    vs = sorted(vals)
    n = len(vs)
    return vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1] + vs[n // 2])


def _transport(transport=None, client=None, rank=None, world=None,
               namespace='ptpu'):
    from ..distributed.collective import HostCollectives
    if transport is not None:
        return transport
    return HostCollectives(client=client, rank=rank, world=world,
                           namespace=namespace)


class ClusterPublisher:
    """One rank's side of the plane: fold the boundary-rate event
    stream into rolling windows and periodically overwrite this rank's
    stats frame on the KV transport.

        pub = ClusterPublisher(transport=hc, interval_s=2.0).install()
        ...train...          # frames publish at steps-flush cadence
        pub.uninstall()

    Publishing triggers from inside the subscriber callback — i.e. at
    the Recorder's boundary rate (steps flushes, compiles, checkpoint
    events), never per step — and is rate-limited to ``interval_s``.
    With no KV client the publisher still aggregates (``frame()``
    works) but ``publish()`` is a no-op."""

    def __init__(self, transport=None, client=None, rank=None,
                 world=None, namespace='ptpu', interval_s=2.0,
                 window_s=60.0, recorder=None):
        self.transport = _transport(transport, client, rank, world,
                                    namespace)
        self.rank = self.transport.rank
        self.interval_s = float(interval_s)
        self.window_s = float(window_s)
        self._lock = threading.RLock()
        self._recorder = recorder
        self._installed = False
        # rolling state (all host-side floats; fed from flushed rows).
        # write() runs on whatever thread emitted the event, so every
        # mutable field below belongs to _lock (the concurrency lint
        # enforces the annotations).
        self.step_ms = RollingWindow(window_s)      # guarded-by: _lock
        self.wait_ms = RollingWindow(window_s)      # guarded-by: _lock
        self.loss = RollingWindow(window_s)         # guarded-by: _lock
        self.cols = {}                              # guarded-by: _lock
        self.coll_ratio = RollingWindow(window_s)   # guarded-by: _lock
        self.last_step = None                       # guarded-by: _lock
        self.last_commit_step = None                # guarded-by: _lock
        self.steps_total = 0                        # guarded-by: _lock
        self.compiles = 0                           # guarded-by: _lock
        self.compile_s = 0.0                        # guarded-by: _lock
        self.retraces = 0                           # guarded-by: _lock
        self.tag = None                             # guarded-by: _lock
        self._seq = 0                               # guarded-by: _lock
        self._last_pub = 0.0                        # guarded-by: _lock
        self.published = 0                          # guarded-by: _lock

    # -- lifecycle -----------------------------------------------------------
    def install(self, recorder=None):
        rec = recorder or self._recorder or get_recorder()
        if not self._installed:
            rec.subscribe(self.write)
            self._recorder = rec
            self._installed = True
        return self

    def uninstall(self):
        if self._installed and self._recorder is not None:
            self._recorder.unsubscribe(self.write)
            self._installed = False
        return self

    def close(self):                    # writer-protocol compatibility
        self.uninstall()

    # -- stream consumption ---------------------------------------------------
    def write(self, rec):
        """Route one boundary-rate record; maybe publish.  Never
        raises (the Recorder swallows subscriber exceptions, but a
        publisher bug must not even cost the swallow)."""
        try:
            kind = rec.get('kind')
            now = _MONO()
            with self._lock:
                if kind == 'steps':
                    self._on_steps(rec, now)
                elif kind == 'compile':
                    self.compiles += 1
                    self.compile_s += rec.get('dur_s') or 0.0
                elif kind == 'retrace':
                    self.retraces += 1
                elif kind == 'collective_observed':
                    us, pred = rec.get('us'), rec.get('predicted_us')
                    if us and pred:
                        self.coll_ratio.add(us / pred, now)
                elif kind in ('checkpoint_commit', 'checkpoint_save'):
                    step = rec.get('step')
                    if step is not None:
                        self.last_commit_step = step
            self.maybe_publish(now)
        except Exception:
            pass

    def _on_steps(self, rec, now):      # locked-by: _lock
        self.tag = rec.get('tag', self.tag)
        n = rec.get('n') or 0
        self.steps_total += n
        hi = rec.get('step_hi')
        if hi is not None:
            self.last_step = (hi if self.last_step is None
                              else max(self.last_step, hi))
        for t in rec.get('step_time_ms') or ():
            if t is not None:
                self.step_ms.add(t, now)
        for w in rec.get('wait_ms') or ():
            if w is not None:
                self.wait_ms.add(w, now)
        for k, col in rec.items():
            if k in ('kind', 'ts', 't', 'rank', 'tag', 'n', 'step',
                     'step_lo', 'step_hi', 'step_time_ms', 'wait_ms'):
                continue
            if not isinstance(col, list):
                continue
            win = (self.loss if k == 'loss' else
                   self.cols.setdefault(k, RollingWindow(self.window_s)))
            for v in col:
                if v is not None:
                    win.add(v, now)

    # -- frames --------------------------------------------------------------
    def frame(self, now=None):
        """This rank's current stats frame (a plain JSON-able dict)."""
        now = now if now is not None else _MONO()
        with self._lock:
            self._seq += 1

            def _mean(win):
                vals = win.values(now)
                return round(sum(vals) / len(vals), 4) if vals else None

            pct = self.step_ms.percentiles(now)
            doc = {
                'v': FRAME_VERSION,
                'rank': self.rank,
                'seq': self._seq,
                'ts': _WALL(),
                'tag': self.tag,
                'step': self.last_step,
                'last_commit_step': self.last_commit_step,
                'steps_total': self.steps_total,
                'step_ms': {k: round(v, 4) if k != 'count' else v
                            for k, v in pct.items()},
                'wait_ms_mean': _mean(self.wait_ms),
                'compiles': self.compiles,
                'compile_s': round(self.compile_s, 4),
                'retraces': self.retraces,
                'coll_ratio': _mean(self.coll_ratio),
                'cols': {k: m for k, m in
                         ((k, _mean(w)) for k, w in self.cols.items())
                         if m is not None},
            }
            vals = self.loss.values(now)
            if vals:
                doc['loss'] = {'last': round(vals[-1], 6),
                               'mean': round(sum(vals) / len(vals), 6),
                               'count': len(vals)}
        # per-rank memory columns (memory observatory): the sampler's
        # last gauges, read at frame rate — absent when the sampler is
        # off, so frames stay byte-compatible with the pre-memory wire
        try:
            gauges = get_recorder().gauges
            for field, key in (('mem_device_bytes', 'memory.device_bytes'),
                               ('mem_peak_bytes',
                                'memory.device_peak_bytes'),
                               ('mem_host_rss', 'memory.host_rss')):
                v = gauges.get(key)
                if v is not None:
                    doc[field] = int(v)
        except Exception:
            pass
        return doc

    def maybe_publish(self, now=None):
        now = now if now is not None else _MONO()
        with self._lock:
            if now - self._last_pub < self.interval_s:
                return False
            # claim the slot BEFORE posting: write() runs on every
            # emitter thread, and an unlocked check-then-act here let
            # two threads pass the rate gate and double-post the frame
            self._last_pub = now
        return self._post(now)

    def publish(self, now=None):
        """Build + post one frame now (rate limit bypassed)."""
        now = now if now is not None else _MONO()
        with self._lock:
            self._last_pub = now
        return self._post(now)

    def _post(self, now):
        # the KV post runs UNLOCKED — a network RTT under _lock would
        # stall every event emitter behind the subscriber callback
        ok = self.transport.post_stats(self.frame(now))
        if ok:
            with self._lock:
                self.published += 1
        return ok


# -- pure attribution / breakdown helpers (unit-testable) ---------------------

def attribute_straggler(per_rank, skew_threshold=1.75,
                        behind_threshold=2, hb_stale_s=None):
    """Who is holding the cluster back, and why.

    ``per_rank``: {rank: row} where each row may carry ``compute_ms``
    (pre-collective host/device work — the discriminating signal in a
    BSP step, where the *total* step time equalizes through the
    collective barrier), ``step_p50_ms``, ``step`` (last step id),
    ``stale`` (frame stale flag) and ``hb_age_s``.

    Returns ``{'rank', 'skew', 'behind', 'cause', 'hb_stale'}`` or
    None.  Causes, in precedence order:

    * ``compute_skew`` — one rank's rolling compute time is
      ``skew_threshold`` x the median of its PEERS (leave-one-out:
      with a median over all ranks a 2-rank cluster could never
      exceed 2x however slow the straggler) — the throttled-rank
      signature: every peer's *collective wait* inflates equally,
      but only the straggler's *compute* does;
    * ``step_skew`` — same test on total step time (no split
      available; still catches non-lockstep loops);
    * ``behind`` — a rank's last published step trails the cluster
      max by ``behind_threshold`` steps or more;
    * ``stale`` — a rank stopped publishing (frame stale / missing)
      while peers progressed: dead or wedged."""
    if not per_rank:
        return None

    def _skew_on(field):
        vals = {r: row.get(field) for r, row in per_rank.items()
                if not row.get('stale') and row.get(field) is not None}
        if len(vals) < 2:
            return None
        worst = max(vals, key=lambda r: vals[r])
        # leave-one-out baseline: the median of the candidate's PEERS
        base = _median([v for r, v in vals.items() if r != worst])
        skew = vals[worst] / max(base, 1e-9)
        return (worst, round(skew, 4)) if skew >= skew_threshold \
            else None

    steps = [row.get('step') for row in per_rank.values()
             if row.get('step') is not None]
    max_step = max(steps) if steps else None

    def _result(rank, cause, skew=None):
        row = per_rank[rank]
        behind = (max_step - row['step']
                  if max_step is not None and row.get('step') is not None
                  else None)
        hb = row.get('hb_age_s')
        return {'rank': rank, 'cause': cause, 'skew': skew,
                'behind': behind,
                'hb_age_s': hb,
                'hb_stale': (hb is not None and hb_stale_s is not None
                             and hb > hb_stale_s)}

    hit = _skew_on('compute_ms')
    if hit:
        return _result(hit[0], 'compute_skew', hit[1])
    hit = _skew_on('step_p50_ms')
    if hit:
        return _result(hit[0], 'step_skew', hit[1])
    if max_step is not None:
        laggards = {r: max_step - row['step']
                    for r, row in per_rank.items()
                    if row.get('step') is not None
                    and max_step - row['step'] >= behind_threshold}
        if laggards:
            worst = max(laggards, key=lambda r: laggards[r])
            return _result(worst, 'behind')
    stale = [r for r, row in per_rank.items() if row.get('stale')]
    if stale and len(stale) < len(per_rank):
        # peers progressed while this rank went quiet
        return _result(stale[0], 'stale')
    return None


def critical_path(per_rank):
    """The cluster's per-step critical-path breakdown from the
    per-rank rows: the step is paced by the SLOWEST rank's compute,
    then the wire, and every faster rank's extra collective time is
    time spent *waiting on the straggler*.

    * ``compute_ms``   — max over ranks (the pacing rank's work);
    * ``collective_ms`` — min over ranks (the last-to-arrive rank
      waits least: its collective time is closest to pure wire);
    * ``straggler_wait_ms`` — max minus min collective time (what the
      fastest ranks burn waiting);
    * ``host_wait_ms`` — max input-pipeline wait;
    * ``step_ms``      — max rolling p50 step time.

    Components a deployment's frames don't carry are simply absent."""
    rows = [r for r in per_rank.values() if not r.get('stale')]
    if not rows:
        return {}

    def _vals(field):
        return [r[field] for r in rows if r.get(field) is not None]

    out = {}
    steps = _vals('step_p50_ms')
    if steps:
        out['step_ms'] = round(max(steps), 4)
    comp = _vals('compute_ms')
    if comp:
        out['compute_ms'] = round(max(comp), 4)
    coll = _vals('coll_ms')
    if coll:
        out['collective_ms'] = round(min(coll), 4)
        if len(coll) > 1:
            out['straggler_wait_ms'] = round(max(coll) - min(coll), 4)
    waits = _vals('wait_ms_mean')
    if waits:
        out['host_wait_ms'] = round(max(waits), 4)
    return out


def loss_divergence(per_rank, band=0.25):
    """Cross-rank loss-divergence digest: the relative spread of the
    per-rank rolling loss means.  In data-parallel SPMD the post-sync
    loss is identical on every rank — any sustained spread means a
    rank is training on different state (corrupt restore, a collective
    fault that leaked, a desynced rng stream)."""
    losses = {r: row.get('loss_mean') for r, row in per_rank.items()
              if not row.get('stale') and row.get('loss_mean') is not None}
    if len(losses) < 2:
        return None
    vals = sorted(losses.values())
    med = vals[len(vals) // 2]
    scale = max(abs(med), 1e-9)
    spread = (vals[-1] - vals[0]) / scale
    return {'spread': round(spread, 6),
            'divergent': spread > band,
            'band': band,
            'per_rank': {r: round(v, 6) for r, v in sorted(losses.items())}}


class ClusterAggregator:
    """Rank 0's join of every rank's stats frames into one live
    cluster view.

        agg = ClusterAggregator(transport=hc, world=8)
        agg.snapshot()      # the /cluster/status.json document
        agg.prometheus()    # /metrics families

    ``collect()`` is purely non-blocking (``read_all_stats`` +
    heartbeat reads); a missing, torn, or stale frame degrades the
    view (rank marked ``stale`` with its last-seen evidence) and can
    never raise out of a scrape.  Attached monitors'
    ``observe_cluster(view)`` hooks run after every collect — that is
    where ``straggler_suspect`` / ``rank_divergence`` latch."""

    def __init__(self, transport=None, client=None, rank=None,
                 world=None, namespace='ptpu', stale_after_s=6.0,
                 skew_threshold=1.75, behind_threshold=2,
                 divergence_band=0.25, min_collect_gap_s=0.1,
                 clock_tolerance_s=30.0):
        self.transport = _transport(transport, client, rank, world,
                                    namespace)
        self.world = self.transport.world
        self.stale_after_s = float(stale_after_s)
        # wall-clock staleness fallback bound: catches a frame that
        # was ALREADY ancient when this aggregator first saw it
        # (aggregator restart next to a dead rank) without letting
        # ordinary NTP offset false-mark healthy hosts
        self.clock_tolerance_s = max(float(clock_tolerance_s),
                                     self.stale_after_s)
        self.skew_threshold = float(skew_threshold)
        self.behind_threshold = int(behind_threshold)
        self.divergence_band = float(divergence_band)
        self.min_collect_gap_s = float(min_collect_gap_s)
        # Mutable aggregator state below is guarded by _lock: collect()
        # may be called from a scrape thread (httpd handler) while a
        # monitor attaches from the trainer thread.
        self.monitors = []              # guarded-by: _lock
        self._lock = threading.RLock()
        self._last_view = None          # guarded-by: _lock
        self._last_collect = 0.0        # guarded-by: _lock
        self._t0 = _MONO()
        # staleness is judged on THIS process's monotonic clock: a
        # rank is stale when its frame seq has not advanced for
        # stale_after_s of observation time.  Comparing the frame's
        # wall-clock ts against ours would falsely stale-mark every
        # healthy rank on a host whose clock is offset by more than
        # stale_after_s (pods give no NTP guarantee — the same reason
        # run_report anchors per-host clock skew).
        self._seen = {}  # rank -> [seq, first_seen_mono]  # guarded-by: _lock

    def attach_monitor(self, monitor):
        with self._lock:
            self.monitors.append(monitor)
        return monitor

    # -- the join ------------------------------------------------------------
    def collect(self, now=None):
        """Read every rank's latest frame + heartbeat and rebuild the
        view.  Rate-limited to ``min_collect_gap_s`` (a scrape storm
        re-reads cached state).  Never raises."""
        now = now if now is not None else _MONO()
        with self._lock:
            if (self._last_view is not None
                    and now - self._last_collect < self.min_collect_gap_s):
                return self._last_view
            try:
                view = self._build_view()
            except Exception as e:      # a scrape must never crash
                view = {'v': FRAME_VERSION, 'error': repr(e)[:200],
                        'world': self.world, 'ranks': {},
                        'degraded': True}
            self._last_view = view
            self._last_collect = now
            monitors = list(self.monitors)
        for m in monitors:
            try:
                m.observe_cluster(view)
            except Exception:
                pass                    # observers never block
        self._maybe_probe_divergence(view)
        return view

    def _maybe_probe_divergence(self, view):
        """On the rank_divergence edge, diff the collective rings
        once (latched until the spread re-enters its band): if the
        divergence came from a leaked/mismatched collective, the
        ``collective_mismatch`` event names the call site.  Rank 0
        only; never raises."""
        div = (view or {}).get('loss_divergence') or {}
        if not div.get('divergent'):
            self._div_probed = False
            return
        if getattr(self, '_div_probed', False):
            return
        self._div_probed = True
        if getattr(self.transport, 'rank', 0) != 0:
            return
        try:
            from ..distributed.collective import probe_mismatch
            probe_mismatch(self.transport, trigger='rank_divergence')
        except Exception:
            pass

    def _build_view(self):  # locked-by: _lock
        wall = _WALL()
        frames = {}
        try:
            frames = self.transport.read_all_stats()
        except Exception:
            pass
        try:
            heartbeats = self.transport.read_heartbeats()
        except Exception:
            heartbeats = {}
        per_rank, missing, stale = {}, [], []
        for r in range(self.world):
            f = frames.get(r)
            if not isinstance(f, dict) or f.get('v') != FRAME_VERSION:
                missing.append(r)
                row = {'stale': True, 'missing': True}
                hb = heartbeats.get(r)
                if hb is not None:
                    row['hb_age_s'] = round(hb, 3)
                per_rank[r] = row
                continue
            # age = how long THIS observer has seen the same seq
            # (clock-offset-immune); a frame may also self-declare
            # publisher-side age for display via its ts, but the
            # staleness DECISION never trusts a remote wall clock
            now_mono = _MONO()
            seen = self._seen.get(r)
            if seen is None or seen[0] != f.get('seq'):
                self._seen[r] = seen = [f.get('seq'), now_mono]
            age = now_mono - seen[1]
            wall_age = wall - (f.get('ts') or 0)
            if wall_age > self.clock_tolerance_s:
                is_stale = True
                age = max(age, wall_age)
            else:
                is_stale = age > self.stale_after_s
            if is_stale:
                stale.append(r)
            pct = f.get('step_ms') or {}
            cols = f.get('cols') or {}
            loss = f.get('loss') or {}
            row = {
                'seq': f.get('seq'),
                'age_s': round(age, 3),
                'stale': is_stale,
                'tag': f.get('tag'),
                'step': f.get('step'),
                'last_commit_step': f.get('last_commit_step'),
                'steps_total': f.get('steps_total'),
                'step_p50_ms': pct.get('p50'),
                'step_p99_ms': pct.get('p99'),
                'step_mean_ms': pct.get('mean'),
                'wait_ms_mean': f.get('wait_ms_mean'),
                'compiles': f.get('compiles'),
                'retraces': f.get('retraces'),
                'coll_ratio': f.get('coll_ratio'),
                'loss_mean': loss.get('mean'),
                'loss_last': loss.get('last'),
                'mem_device_bytes': f.get('mem_device_bytes'),
                'mem_peak_bytes': f.get('mem_peak_bytes'),
                'mem_host_rss': f.get('mem_host_rss'),
            }
            for k, v in cols.items():
                row.setdefault(k, v)
            hb = heartbeats.get(r)
            if hb is not None:
                row['hb_age_s'] = round(hb, 3)
            per_rank[r] = row
        steps = [row.get('step') for row in per_rank.values()
                 if row.get('step') is not None]
        max_step = max(steps) if steps else None
        # per-rank skew vs the cluster median step p50 (rendered even
        # when no rank crosses the straggler threshold)
        med_p50 = _median([row['step_p50_ms']
                           for row in per_rank.values()
                           if row.get('step_p50_ms') is not None
                           and not row.get('stale')])
        for r, row in per_rank.items():
            if max_step is not None and row.get('step') is not None:
                row['behind'] = max_step - row['step']
            if med_p50 and row.get('step_p50_ms') is not None:
                row['skew'] = round(row['step_p50_ms'] / med_p50, 4)
        # memory skew (memory observatory): per-rank live bytes vs the
        # cluster median — a rank running hot on HBM is the next OOM
        med_mem = _median([row['mem_device_bytes']
                           for row in per_rank.values()
                           if row.get('mem_device_bytes')
                           and not row.get('stale')])
        for r, row in per_rank.items():
            if med_mem and row.get('mem_device_bytes'):
                row['mem_skew'] = round(
                    row['mem_device_bytes'] / med_mem, 4)
        straggler = attribute_straggler(
            per_rank, skew_threshold=self.skew_threshold,
            behind_threshold=self.behind_threshold,
            hb_stale_s=self.stale_after_s)
        div = loss_divergence(per_rank, band=self.divergence_band)
        # collective flight recorder join: per-rank ring heads + the
        # cross-rank diff (non-blocking cledger reads; absent when the
        # ledger is off or no rank has published a ring yet)
        coll = None
        try:
            from ..distributed.collective import (
                LEDGER_KEY, diff_ledgers)
            led = self.transport.read_all_stats(key=LEDGER_KEY)
            if led:
                coll = {'ranks': {
                    str(r): {'seq': f.get('seq'),
                             'step': f.get('step'),
                             'last': (f.get('entries') or [None])[-1]}
                    for r, f in sorted(led.items())}}
                d = diff_ledgers(led)
                if d is not None:
                    coll['diff'] = d
        except Exception:
            coll = None
        view = {
            'v': FRAME_VERSION,
            'ts': round(wall, 3),
            'uptime_s': round(_MONO() - self._t0, 3),
            'world': self.world,
            'max_step': max_step,
            'ranks': {str(r): row for r, row in sorted(per_rank.items())},
            'missing': missing,
            'stale': stale,
            'degraded': bool(missing or stale),
            'straggler': straggler,
            'critical_path': critical_path(per_rank),
            'loss_divergence': div,
            'collectives': coll,
        }
        return view

    # -- reads (httpd source protocol: snapshot + prometheus) ----------------
    def snapshot(self, now=None):
        return self.collect(now)

    def prometheus(self, now=None):
        """The cluster families for /metrics (``paddle_tpu_cluster_``
        prefix; rank-labelled gauges)."""
        view = self.collect(now)
        out = []

        def fam(name, mtype, help_, rows):
            emitted = False
            for labels, value in rows:
                if value is None:
                    continue
                if not emitted:
                    out.append(f'# HELP paddle_tpu_cluster_{name} '
                               f'{help_}')
                    out.append(f'# TYPE paddle_tpu_cluster_{name} '
                               f'{mtype}')
                    emitted = True
                lbl = ('{' + ','.join(
                    f'{k}="{v}"' for k, v in sorted(labels.items()))
                    + '}') if labels else ''
                out.append(f'paddle_tpu_cluster_{name}{lbl} {value}')

        ranks = view.get('ranks', {})
        fam('world_size', 'gauge', 'configured cluster world size',
            [({}, view.get('world'))])
        fam('max_step', 'gauge', 'highest step any rank published',
            [({}, view.get('max_step'))])
        fam('degraded', 'gauge',
            '1 when any rank frame is missing or stale',
            [({}, int(bool(view.get('degraded'))))])
        fam('rank_step', 'gauge', 'last step each rank published',
            [({'rank': r}, row.get('step'))
             for r, row in ranks.items()])
        fam('rank_behind', 'gauge',
            'steps each rank trails the cluster max',
            [({'rank': r}, row.get('behind'))
             for r, row in ranks.items()])
        fam('rank_step_p50_ms', 'gauge',
            'rolling p50 step time per rank (ms)',
            [({'rank': r}, row.get('step_p50_ms'))
             for r, row in ranks.items()])
        fam('rank_skew', 'gauge',
            'rank step-time p50 over the cluster median',
            [({'rank': r}, row.get('skew'))
             for r, row in ranks.items()])
        fam('rank_stale', 'gauge',
            '1 when the rank frame is older than stale_after_s',
            [({'rank': r}, int(bool(row.get('stale'))))
             for r, row in ranks.items()])
        fam('rank_frame_age_s', 'gauge', 'stats frame age per rank',
            [({'rank': r}, row.get('age_s'))
             for r, row in ranks.items()])
        fam('rank_hb_age_s', 'gauge',
            'watchdog heartbeat age per rank',
            [({'rank': r}, row.get('hb_age_s'))
             for r, row in ranks.items()])
        fam('rank_compiles', 'counter', 'compile events per rank',
            [({'rank': r}, row.get('compiles'))
             for r, row in ranks.items()])
        fam('rank_loss_mean', 'gauge',
            'rolling loss-window mean per rank',
            [({'rank': r}, row.get('loss_mean'))
             for r, row in ranks.items()])
        fam('rank_mem_device_bytes', 'gauge',
            'live device bytes per rank (memory sampler)',
            [({'rank': r}, row.get('mem_device_bytes'))
             for r, row in ranks.items()])
        fam('rank_mem_host_rss_bytes', 'gauge',
            'host RSS per rank (memory sampler)',
            [({'rank': r}, row.get('mem_host_rss'))
             for r, row in ranks.items()])
        fam('rank_mem_skew', 'gauge',
            'rank live device bytes over the cluster median',
            [({'rank': r}, row.get('mem_skew'))
             for r, row in ranks.items()])
        strag = view.get('straggler')
        fam('straggler_rank', 'gauge',
            'attributed straggler rank (-1 when none)',
            [({}, strag['rank'] if strag else -1)])
        if strag:
            fam('straggler_skew', 'gauge',
                "the attributed straggler's skew factor",
                [({}, strag.get('skew'))])
        cp = view.get('critical_path') or {}
        fam('critical_path_ms', 'gauge',
            'per-step critical-path component (ms)',
            [({'component': k.replace('_ms', '')}, v)
             for k, v in sorted(cp.items())])
        div = view.get('loss_divergence')
        if div:
            fam('loss_spread', 'gauge',
                'relative cross-rank loss-window spread',
                [({}, div.get('spread'))])
        return '\n'.join(out) + '\n'


class ClusterPlane:
    """One process's handle on the whole plane: the publisher (every
    rank), plus — on the aggregating rank — the aggregator, its
    monitors, and the HTTP source registration.  ``close()`` tears all
    of it down (idempotent)."""

    def __init__(self, publisher=None, aggregator=None, server=None,
                 owns_server=False):
        self.publisher = publisher
        self.aggregator = aggregator
        self.server = server
        self.owns_server = owns_server

    @property
    def port(self):
        return self.server.port if self.server is not None else None

    def close(self):
        if self.publisher is not None:
            try:
                # flush the final frame: a short run (or an interval
                # longer than the tail of the job) must not leave the
                # cluster view showing pre-warmup state forever
                self.publisher.publish()
            except Exception:
                pass
            self.publisher.uninstall()
            self.publisher = None
        if self.server is not None:
            try:
                if self.owns_server:
                    self.server.stop()
                else:
                    self.server.remove_source('cluster')
            except Exception:
                pass
            self.server = None
        self.aggregator = None


def enable_cluster_plane(transport=None, client=None, rank=None,
                         world=None, namespace='ptpu', interval_s=2.0,
                         window_s=60.0, aggregate=None, serve=None,
                         port=None, stale_after_s=None, monitors=True):
    """Wire the whole plane for this process:

    * every rank: a :class:`ClusterPublisher` subscribed to the global
      Recorder;
    * the aggregating rank (``aggregate=None`` -> rank 0): a
      :class:`ClusterAggregator` with ``straggler_suspect`` /
      ``rank_divergence`` monitors attached, registered as the
      ``cluster`` source on a :class:`telemetry.httpd.MetricsServer`
      — an already-running server in this process is reused (one
      port for serving + cluster views); otherwise one is started
      when a port resolves (``port=`` / PADDLE_TPU_METRICS_PORT;
      ``serve=False`` skips HTTP entirely).

    Returns a :class:`ClusterPlane` (``plane.close()`` to tear down).
    """
    tr = _transport(transport, client, rank, world, namespace)
    plane = ClusterPlane(
        publisher=ClusterPublisher(transport=tr,
                                   interval_s=interval_s,
                                   window_s=window_s).install())
    is_agg = (tr.rank == 0) if aggregate is None else bool(aggregate)
    if not is_agg:
        return plane
    kwargs = {}
    if stale_after_s is not None:
        kwargs['stale_after_s'] = stale_after_s
    agg = ClusterAggregator(transport=tr, **kwargs)
    if monitors:
        from .monitors import SLOMonitor, DriftMonitor
        agg.attach_monitor(SLOMonitor())
        agg.attach_monitor(DriftMonitor())
    plane.aggregator = agg
    if serve is False:
        return plane
    from .httpd import attach_source, resolve_metrics_port
    if serve is True and port is None:
        resolved = 0                    # force HTTP: ephemeral port
    else:
        resolved = resolve_metrics_port(port)
    try:
        server, created = attach_source('cluster', agg, port=resolved)
    except Exception:
        server, created = None, False
    plane.server = server
    plane.owns_server = created
    return plane
