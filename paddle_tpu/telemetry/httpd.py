"""Stdlib-only HTTP status server over a LiveAggregator.

Four read-only routes, enough for a human with curl, a Prometheus
scraper, and a load balancer's health check:

* ``/healthz``        — liveness: ``{"ok": true, "uptime_s": …}``
* ``/status.json``    — the aggregator's full rolling snapshot
  (latency percentiles, rates, gauges, alerts, traced rids)
* ``/metrics``        — Prometheus text exposition format
* ``/requests/<rid>`` — one request's lifecycle trace (finished
  requests from the bounded ``serve_trace`` store; in-flight ones via
  the engine's live hook), 404 when unknown

Serving happens on daemon threads (ThreadingHTTPServer); every
response is computed from the aggregator's host-side rolling state
under its lock — a scrape NEVER touches a device array, a compiled
module, or the engine's scheduler structures, which is what makes
"scraping /metrics mid-run changes no numerics and adds no syncs"
provable (bench ``--obs-smoke`` and the bit-exactness test pin it).

Security note: binds ``127.0.0.1`` by default — metrics can leak
prompts' shape/timing and the trace view leaks rids; exporting the
port off-host is an explicit operator decision
(``PADDLE_TPU_METRICS_HOST=0.0.0.0``).

Off by default everywhere: construct+start explicitly, or let
``ServingEngine(serve_metrics_port=…)`` / ``PADDLE_TPU_METRICS_PORT``
do it (see :func:`resolve_metrics_port` for the posture).
"""
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ['MetricsServer', 'resolve_metrics_port',
           'METRICS_PORT_ENV', 'METRICS_HOST_ENV']

METRICS_PORT_ENV = 'PADDLE_TPU_METRICS_PORT'
METRICS_HOST_ENV = 'PADDLE_TPU_METRICS_HOST'


def resolve_metrics_port(arg=None):
    """The shared opt-in posture (mirrors ``resolve_watchdog``):
    explicit ``False`` -> None (off even if the env says on); an int
    passes through (0 = bind an ephemeral port — tests);``None`` ->
    the PADDLE_TPU_METRICS_PORT env decides, where unset/'0'/'off'/
    'false' mean off.  Returns a port int or None."""
    if arg is False:
        return None
    if arg is not None:
        return int(arg)
    text = (os.environ.get(METRICS_PORT_ENV) or '').strip().lower()
    if text in ('', '0', 'off', 'false'):
        return None
    return int(text)


class _Handler(BaseHTTPRequestHandler):
    # the server instance carries .aggregator (set by MetricsServer)
    protocol_version = 'HTTP/1.1'

    def log_message(self, *args):       # no stderr chatter per scrape
        pass

    def _send(self, code, body, ctype='application/json'):
        data = body if isinstance(body, bytes) else body.encode('utf-8')
        self.send_response(code)
        self.send_header('Content-Type', f'{ctype}; charset=utf-8')
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass                        # scraper went away mid-write

    def do_GET(self):                   # noqa: N802 (http.server API)
        agg = self.server.aggregator
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if path == '/healthz':
                self._send(200, json.dumps(
                    {'ok': True,
                     'uptime_s': agg.snapshot().get('uptime_s')}))
            elif path == '/status.json':
                self._send(200, json.dumps(agg.snapshot(), indent=1))
            elif path == '/metrics':
                self._send(200, agg.prometheus(),
                           ctype='text/plain; version=0.0.4')
            elif path.startswith('/requests/'):
                rid = path[len('/requests/'):]
                doc = agg.request_trace(rid)
                if doc is None:
                    self._send(404, json.dumps(
                        {'error': f'unknown rid {rid!r}'}))
                else:
                    self._send(200, json.dumps(doc, indent=1))
            elif path == '/':
                self._send(200, json.dumps({'routes': [
                    '/healthz', '/status.json', '/metrics',
                    '/requests/<rid>']}))
            else:
                self._send(404, json.dumps({'error': 'not found'}))
        except Exception as e:          # a scrape must never crash it
            try:
                self._send(500, json.dumps({'error': repr(e)[:200]}))
            except Exception:
                pass


class MetricsServer:
    """One live-metrics HTTP endpoint over one aggregator.

        srv = MetricsServer(agg, port=0).start()
        ... http://127.0.0.1:{srv.port}/status.json ...
        srv.stop()
    """

    def __init__(self, aggregator, port=0, host=None):
        self.aggregator = aggregator
        self.requested_port = int(port)
        self.host = host or os.environ.get(METRICS_HOST_ENV,
                                           '127.0.0.1')
        self._httpd = None
        self._thread = None
        self.port = None

    def start(self):
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        httpd.aggregator = self.aggregator
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._thread = threading.Thread(
            target=httpd.serve_forever, name='paddle-tpu-metrics',
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        t, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if t is not None:
            t.join(timeout=5.0)

    @property
    def url(self):
        return (None if self.port is None
                else f'http://{self.host}:{self.port}')

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
