"""Stdlib-only HTTP status server over a LiveAggregator.

Four read-only routes, enough for a human with curl, a Prometheus
scraper, and a load balancer's health check:

* ``/healthz``        — liveness: ``{"ok": true, "uptime_s": …}``
* ``/status.json``    — the aggregator's full rolling snapshot
  (latency percentiles, rates, gauges, alerts, traced rids)
* ``/metrics``        — Prometheus text exposition format
* ``/requests/<rid>`` — one request's lifecycle trace (finished
  requests from the bounded ``serve_trace`` store; in-flight ones via
  the engine's live hook), 404 when unknown

One server, many views: besides the primary aggregator a server
carries a small **source registry** (``add_source(name, src)`` — any
object with ``snapshot()``/``prometheus()``), so one process exposes
the serving AND cluster planes on ONE port instead of double-binding:

* ``/<name>/status.json`` — that source's snapshot
  (``/cluster/status.json`` for the training-cluster view)
* ``/<name>/metrics``     — that source's families alone
* ``/metrics``            — the primary's families plus EVERY
  registered source's, concatenated (one scrape config per process)

``attach_source(name, src, port=…)`` is the module-level helper that
reuses a server already running in this process (whoever bound first
— typically the ServingEngine) or starts one.

Serving happens on daemon threads (ThreadingHTTPServer); every
response is computed from the aggregator's host-side rolling state
under its lock — a scrape NEVER touches a device array, a compiled
module, or the engine's scheduler structures, which is what makes
"scraping /metrics mid-run changes no numerics and adds no syncs"
provable (bench ``--obs-smoke`` and the bit-exactness test pin it).

Security note: binds ``127.0.0.1`` by default — metrics can leak
prompts' shape/timing and the trace view leaks rids; exporting the
port off-host is an explicit operator decision
(``PADDLE_TPU_METRICS_HOST=0.0.0.0``).

Off by default everywhere: construct+start explicitly, or let
``ServingEngine(serve_metrics_port=…)`` / ``PADDLE_TPU_METRICS_PORT``
do it (see :func:`resolve_metrics_port` for the posture).
"""
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ['MetricsServer', 'resolve_metrics_port', 'attach_source',
           'running_servers', 'METRICS_PORT_ENV', 'METRICS_HOST_ENV']

METRICS_PORT_ENV = 'PADDLE_TPU_METRICS_PORT'
METRICS_HOST_ENV = 'PADDLE_TPU_METRICS_HOST'


def resolve_metrics_port(arg=None):
    """The shared opt-in posture (mirrors ``resolve_watchdog``):
    explicit ``False`` -> None (off even if the env says on); an int
    passes through (0 = bind an ephemeral port — tests);``None`` ->
    the PADDLE_TPU_METRICS_PORT env decides, where unset/'0'/'off'/
    'false' mean off.  Returns a port int or None."""
    if arg is False:
        return None
    if arg is not None:
        return int(arg)
    text = (os.environ.get(METRICS_PORT_ENV) or '').strip().lower()
    if text in ('', '0', 'off', 'false'):
        return None
    return int(text)


class _Handler(BaseHTTPRequestHandler):
    # the server instance carries .aggregator (set by MetricsServer)
    protocol_version = 'HTTP/1.1'

    def log_message(self, *args):       # no stderr chatter per scrape
        pass

    def _send(self, code, body, ctype='application/json'):
        data = body if isinstance(body, bytes) else body.encode('utf-8')
        self.send_response(code)
        self.send_header('Content-Type', f'{ctype}; charset=utf-8')
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass                        # scraper went away mid-write

    def do_GET(self):                   # noqa: N802 (http.server API)
        agg = self.server.aggregator
        sources = getattr(self.server, 'sources', {})
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if path == '/healthz':
                up = (agg.snapshot().get('uptime_s')
                      if agg is not None else None)
                self._send(200, json.dumps(
                    {'ok': True, 'uptime_s': up,
                     'sources': sorted(sources)}))
            elif path == '/status.json':
                if agg is None:
                    self._send(404, json.dumps(
                        {'error': 'no primary aggregator',
                         'sources': sorted(sources)}))
                else:
                    self._send(200, json.dumps(agg.snapshot(),
                                               indent=1))
            elif path == '/metrics':
                # the primary's families plus every registered
                # source's — one scrape endpoint per process.  A
                # broken source degrades to its name in a comment,
                # never a dead scrape.
                parts = []
                if agg is not None:
                    parts.append(agg.prometheus())
                for name, src in sorted(sources.items()):
                    try:
                        parts.append(src.prometheus())
                    except Exception:
                        parts.append(f'# source {name} failed\n')
                self._send(200, ''.join(parts) or '\n',
                           ctype='text/plain; version=0.0.4')
            elif path.startswith('/requests/'):
                if agg is None:
                    self._send(404, json.dumps(
                        {'error': 'no primary aggregator'}))
                    return
                rid = path[len('/requests/'):]
                doc = agg.request_trace(rid)
                if doc is None:
                    self._send(404, json.dumps(
                        {'error': f'unknown rid {rid!r}'}))
                else:
                    self._send(200, json.dumps(doc, indent=1))
            elif path == '/memory.json':
                # the memory observatory's three-way table (predicted
                # vs compiled vs live) — module-global state, so every
                # metrics server in the process serves it without any
                # wiring
                from . import memory as _mem
                self._send(200, json.dumps(_mem.snapshot(), indent=1))
            elif self._try_source(path, sources):
                pass
            elif path == '/':
                routes = ['/healthz', '/status.json', '/metrics',
                          '/requests/<rid>', '/memory.json']
                for name in sorted(sources):
                    routes += [f'/{name}/status.json',
                               f'/{name}/metrics']
                self._send(200, json.dumps({'routes': routes}))
            else:
                self._send(404, json.dumps({'error': 'not found'}))
        except Exception as e:          # a scrape must never crash it
            try:
                self._send(500, json.dumps({'error': repr(e)[:200]}))
            except Exception:
                pass

    def _try_source(self, path, sources):
        """Serve /<name>/status.json | /<name>/metrics for a
        registered source; False when the path is not source-shaped."""
        parts = path.lstrip('/').split('/')
        if len(parts) != 2 or parts[0] not in sources:
            return False
        src = sources[parts[0]]
        if parts[1] == 'status.json':
            self._send(200, json.dumps(src.snapshot(), indent=1))
        elif parts[1] == 'metrics':
            self._send(200, src.prometheus(),
                       ctype='text/plain; version=0.0.4')
        else:
            self._send(404, json.dumps({'error': 'not found'}))
        return True


class MetricsServer:
    """One live-metrics HTTP endpoint over one (optional) primary
    aggregator plus any number of named sources.

        srv = MetricsServer(agg, port=0).start()
        srv.add_source('cluster', cluster_agg)
        ... http://127.0.0.1:{srv.port}/cluster/status.json ...
        srv.stop()

    ``aggregator=None`` starts a registry-only server (the training
    cluster plane with no serving engine in-process).  A source is any
    object with ``snapshot()`` and ``prometheus()``.
    """

    # names the fixed routes own — a source may not shadow them
    _RESERVED = ('healthz', 'status.json', 'metrics', 'requests')

    def __init__(self, aggregator=None, port=0, host=None):
        self.aggregator = aggregator
        self.sources = {}
        self.requested_port = int(port)
        self.host = host or os.environ.get(METRICS_HOST_ENV,
                                           '127.0.0.1')
        self._httpd = None
        self._thread = None
        self.port = None

    # -- source registry -----------------------------------------------------
    def add_source(self, name, source):
        """Register `source` under `name` (routes
        ``/<name>/status.json`` + ``/<name>/metrics``, and its
        families join ``/metrics``).  Replaces an existing source of
        the same name."""
        name = str(name).strip('/')
        if not name or '/' in name or name in self._RESERVED:
            raise ValueError(f'bad source name {name!r}')
        if not (hasattr(source, 'snapshot')
                and hasattr(source, 'prometheus')):
            raise TypeError('a metrics source needs snapshot() and '
                            'prometheus()')
        self.sources[name] = source
        if self._httpd is not None:
            self._httpd.sources = self.sources
        return source

    def remove_source(self, name):
        src = self.sources.pop(name, None)
        if self._httpd is not None:
            self._httpd.sources = self.sources
        return src

    def start(self):
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        httpd.aggregator = self.aggregator
        httpd.sources = self.sources
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._thread = threading.Thread(
            target=httpd.serve_forever, name='paddle-tpu-metrics',
            daemon=True)
        self._thread.start()
        _note_running(self)
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        t, self._thread = self._thread, None
        _note_stopped(self)
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if t is not None:
            t.join(timeout=5.0)

    @property
    def url(self):
        return (None if self.port is None
                else f'http://{self.host}:{self.port}')

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


# -- process-wide running-server registry -------------------------------------
#
# The double-binding fix: when a ServingEngine already bound a metrics
# port in this process, the training cluster plane must ADD its view
# there instead of fighting for a second port.  start()/stop() keep
# this list current; attach_source() consults it.

_running = []
_running_lock = threading.Lock()


def _note_running(server):
    with _running_lock:
        if server not in _running:
            _running.append(server)


def _note_stopped(server):
    with _running_lock:
        if server in _running:
            _running.remove(server)


def running_servers():
    """The MetricsServers currently serving in this process (oldest
    first — the first binder is the canonical process endpoint)."""
    with _running_lock:
        return list(_running)


def attach_source(name, source, port=None, host=None):
    """Expose `source` over HTTP on ONE port per process: reuse the
    process's already-running MetricsServer when there is one (the
    source registry — serving + cluster views together), else start a
    fresh registry-only server on `port`.  ``port=None`` with no
    running server means no HTTP (the caller did not opt in) —
    returns (None, False).  Otherwise returns (server, created)."""
    with _running_lock:
        live = _running[0] if _running else None
    if live is not None:
        live.add_source(name, source)
        return live, False
    if port is None:
        return None, False
    server = MetricsServer(None, port=port, host=host)
    server.add_source(name, source)
    server.start()
    return server, True
