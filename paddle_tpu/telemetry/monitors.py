"""Rolling SLO / drift monitors over the live telemetry stream.

These are the *sensors* ROADMAP item 3's self-tuning runtime needs: a
``plan_supervisor`` that re-plans in flight has to be told WHEN — and
"when" is exactly the two typed events emitted here:

* ``slo_breach`` (:class:`SLOMonitor`) — the serving runtime left its
  latency envelope: rolling-window TTFT p99 over the watchdog-derived
  budget (``resilience.watchdog.Budget.ttft_budget_s`` — the same
  deadline machinery that already evicts individual starved requests,
  lifted to the aggregate), or the deadline-eviction *rate* over a
  threshold (requests are being shed, not served).
* ``drift_detected`` (:class:`DriftMonitor`) — the world stopped
  matching the model of it: the windowed observed/predicted us_ratio
  of a profiled collective (PR-8 ``collective_observed`` events carry
  both sides) left its band, or a ``compile`` event landed after the
  run was declared steady (a bucket-set leak / retrace in what should
  be a finite compiled surface).

Monitors attach to a :class:`telemetry.live.LiveAggregator`
(``agg.attach_monitor(...)``) and observe the same boundary-rate
records it routes — nothing here runs per device step or touches a
device array.  Both monitors **latch**: a sustained breach fires ONE
event, re-arming only after the signal returns inside its band (with
hysteresis), so a supervisor sees edges, not a firehose — and the
seeded drift-injection acceptance ("inflate one collective's observed
us → exactly one ``drift_detected``") holds by construction.

The TRAINING-cluster plane (:mod:`telemetry.cluster`) reuses the same
two monitors through a second hook, ``observe_cluster(view)``, called
by the :class:`~paddle_tpu.telemetry.cluster.ClusterAggregator` after
every collect:

* :class:`SLOMonitor` latches ``straggler_suspect`` — the cluster
  view attributed a straggler (step-time/compute skew, a rank falling
  behind, or a stale frame/heartbeat) — re-arming when the
  attribution clears or moves to another rank;
* :class:`DriftMonitor` latches ``rank_divergence`` — the cross-rank
  loss-window spread left its band: a rank is training on different
  state than its peers.
"""
import time
from collections import deque

__all__ = ['SLOMonitor', 'DriftMonitor', 'MemoryMonitor']

_MONO = time.monotonic


def _emit(kind, **data):
    from . import event
    return event(kind, **data)


class SLOMonitor:
    """Watches the aggregator's serving windows at request-finish
    cadence, every ``check_every``-th finish (never per decoded
    token, never on an unchanged window).

    ttft_budget_s   the aggregate TTFT p99 allowance.  Defaults to the
                    watchdog Budget's first-step allowance
                    (``budget.ttft_budget_s()``) when a budget is
                    given — queueing + prefill ride on the same
                    envelope the per-request deadlines derive from.
    deadline_evict_frac  breach when more than this fraction of the
                    window's finished requests were deadline
                    evictions.
    min_samples     windows thinner than this never fire (startup
                    noise is not an SLO breach).
    """

    def __init__(self, budget=None, ttft_budget_s=None,
                 deadline_evict_frac=0.5, min_samples=8,
                 rearm_frac=0.7, check_every=4):
        if ttft_budget_s is None and budget is not None:
            ttft_budget_s = budget.ttft_budget_s()
        self.ttft_budget_s = (None if ttft_budget_s is None
                              else float(ttft_budget_s))
        self.deadline_evict_frac = float(deadline_evict_frac)
        self.min_samples = int(min_samples)
        self.rearm_frac = float(rearm_frac)
        # the window check sorts up to the full reservoir and runs
        # under the aggregator lock on the emission path: bound it to
        # request-finish cadence AND every Nth finish
        self.check_every = max(1, int(check_every))
        self._seen = 0
        self._latched = set()           # which signals already fired
        self.breaches = []              # local record (tests/reports)

    def observe(self, rec, agg):
        if rec.get('kind') == 'plan_swap':
            # a new plan means new budgets: clear the latch so the
            # NEXT breach (under the new plan) is a fresh edge, not a
            # hangover from the plan the supervisor just retired
            self._latched.clear()
            return
        # TTFT and deadline-eviction state only change when a request
        # finishes — serve_step would re-check an unchanged window
        if rec.get('kind') != 'serve_request':
            return
        self._seen += 1
        if self._seen % self.check_every:
            return
        now = _MONO()
        self._check_ttft(agg, now)
        self._check_deadline_rate(agg, now)

    def _fire(self, what, **data):
        self._latched.add(what)
        ev = _emit('slo_breach', what=what, **data)
        self.breaches.append(ev or dict(kind='slo_breach', what=what,
                                        **data))

    def _check_ttft(self, agg, now):
        if self.ttft_budget_s is None:
            return
        pct = agg.ttft.percentiles(now)
        if pct.get('count', 0) < self.min_samples:
            return
        p99 = pct['p99']
        if 'ttft_p99' in self._latched:
            if p99 <= self.ttft_budget_s * self.rearm_frac:
                self._latched.discard('ttft_p99')    # re-arm
            return
        if p99 > self.ttft_budget_s:
            self._fire('ttft_p99', observed_s=round(p99, 4),
                       budget_s=self.ttft_budget_s,
                       window_count=pct['count'])

    # -- cluster hook (telemetry.cluster.ClusterAggregator) ------------------
    def observe_cluster(self, view):
        """Latch ``straggler_suspect`` off one cluster view: the
        aggregator attributed a straggler and this monitor had not yet
        fired for that rank.  Re-arms when the attribution clears (or
        moves — a NEW straggler rank fires again: the supervisor needs
        every edge, not just the first)."""
        strag = (view or {}).get('straggler')
        if not strag:
            self._latched.discard('straggler')
            self._strag_rank = None
            return
        rank = strag.get('rank')
        if 'straggler' in self._latched \
                and getattr(self, '_strag_rank', None) == rank:
            return
        self._strag_rank = rank
        self._latched.add('straggler')
        # the suspect rides as 'suspect', NOT 'rank': the JSONL writer
        # stamps every record with the EMITTING host's rank (the
        # aggregator's rank 0), which would clobber the attribution
        ev = _emit('straggler_suspect', suspect=rank,
                   cause=strag.get('cause'), skew=strag.get('skew'),
                   behind=strag.get('behind'),
                   hb_stale=strag.get('hb_stale'),
                   world=view.get('world'),
                   max_step=view.get('max_step'))
        self.breaches.append(ev or dict(kind='straggler_suspect',
                                        suspect=rank, **strag))

    def _check_deadline_rate(self, agg, now):
        dl = agg.by_cause.get('deadline')
        if dl is None:
            return
        breached = dl.windowed(now)
        finished = agg.finished.windowed(now)
        if finished < self.min_samples:
            return
        frac = breached / finished
        if 'deadline_evictions' in self._latched:
            if frac <= self.deadline_evict_frac * self.rearm_frac:
                self._latched.discard('deadline_evictions')
            return
        if frac > self.deadline_evict_frac:
            self._fire('deadline_evictions',
                       observed_frac=round(frac, 4),
                       threshold_frac=self.deadline_evict_frac,
                       breached=int(breached), finished=int(finished))


class DriftMonitor:
    """Predicted-vs-observed drift over ``collective_observed`` events
    plus the post-steady compile detector.

    ratio_band      fire when the windowed mean us_ratio of one op's
                    call site leaves [1/band, band] (default 4.0 — an
                    uncalibrated model is routinely ~2x off; 4x is a
                    regime change).
    min_windows     observations of one instr needed before its ratio
                    is trusted.
    warmup_events   ``compile`` events within the aggregator's pre-
                    steady phase are warmup, never drift; after
                    ``agg.mark_steady()`` every compile fires (once,
                    latched per compile name).
    """

    def __init__(self, ratio_band=4.0, min_windows=1, window=8):
        self.ratio_band = float(ratio_band)
        if self.ratio_band <= 1.0:
            raise ValueError('ratio_band must be > 1')
        self.min_windows = int(min_windows)
        self._ratios = {}               # (op, instr) -> deque of ratio
        self._window = int(window)
        self._latched = set()
        self._post_swap_compiles = 0
        self.detections = []            # local record (tests/reports)

    def observe(self, rec, agg):
        kind = rec.get('kind')
        if kind == 'plan_swap':
            # the swapped-in plan predicts with different constants
            # and compiles fresh modules: stale ratio windows (and the
            # retired plan's latches) would mis-attribute the new
            # plan's first observations as drift — or suppress real
            # drift under a recycled latch key
            self._ratios.clear()
            self._latched.clear()
            # the swapped plan's own rebuild (per-step and/or fused
            # module) compiles AFTER steady by construction — it is
            # the actuation, not drift
            self._post_swap_compiles = 2
        elif kind == 'collective_observed':
            self._observe_collective(rec)
        elif kind == 'compile':
            if self._post_swap_compiles > 0:
                self._post_swap_compiles -= 1
                return
            self._observe_compile(rec, agg)

    def _fire(self, cause, key, **data):
        self._latched.add(key)
        ev = _emit('drift_detected', cause=cause, **data)
        self.detections.append(ev or dict(kind='drift_detected',
                                          cause=cause, **data))

    def _observe_collective(self, rec):
        us, pred = rec.get('us'), rec.get('predicted_us')
        if not us or not pred:
            return
        key = (rec.get('op'), rec.get('instr'))
        ratios = self._ratios.setdefault(
            key, deque(maxlen=self._window))
        ratios.append(us / pred)
        if len(ratios) < self.min_windows:
            return
        mean = sum(ratios) / len(ratios)
        lkey = ('us_ratio',) + key
        inside = 1.0 / self.ratio_band <= mean <= self.ratio_band
        if lkey in self._latched:
            # hysteresis: re-arm only once comfortably back in band —
            # halfway between 1.0 and the band edge, so the re-arm
            # window is non-empty for ANY band > 1 (band/2 was empty
            # for band <= 2)
            rearm = 1.0 + (self.ratio_band - 1.0) / 2.0
            if 1.0 / rearm <= mean <= rearm:
                self._latched.discard(lkey)
            return
        if not inside:
            self._fire('us_ratio', lkey, op=rec.get('op'),
                       instr=rec.get('instr'),
                       us_ratio=round(mean, 4),
                       band=self.ratio_band,
                       observed_us=round(us, 3),
                       predicted_us=round(pred, 3),
                       windows=len(ratios))

    def _observe_compile(self, rec, agg):
        if agg.steady_since is None:
            return
        name = rec.get('name', '?')
        lkey = ('compile', name)
        if lkey in self._latched:
            return
        self._fire('post_steady_compile', lkey, name=name,
                   dur_s=rec.get('dur_s'))

    # -- cluster hook (telemetry.cluster.ClusterAggregator) ------------------
    def observe_cluster(self, view):
        """Latch ``rank_divergence`` off one cluster view: the
        cross-rank loss-window spread left its band (a rank trains on
        different state — corrupt restore, leaked collective fault,
        desynced rng).  Hysteresis: re-arms at half the band."""
        div = (view or {}).get('loss_divergence')
        lkey = ('rank_divergence',)
        if not div:
            return
        spread = div.get('spread') or 0.0
        band = div.get('band') or 0.0
        if lkey in self._latched:
            if spread <= band * 0.5:
                self._latched.discard(lkey)
            return
        if div.get('divergent'):
            self._latched.add(lkey)
            ev = _emit('rank_divergence', spread=spread, band=band,
                       per_rank=div.get('per_rank'),
                       world=view.get('world'),
                       max_step=view.get('max_step'))
            self.detections.append(ev or dict(kind='rank_divergence',
                                              spread=spread))


class MemoryMonitor:
    """Live HBM high-water vs budget, latched exactly-once.

    Observes the boundary-rate ``memory_sample`` records the
    :class:`telemetry.memory.MemorySampler` emits (device bytes from
    ``memory_stats()`` on TPU, the live-arrays census on CPU) and
    fires ONE ``memory_pressure`` event when the live bytes cross
    ``budget_bytes * watermark`` — the edge the plan supervisor
    re-plans on with a tightened ``hbm_budget_gb``.  Re-arms with
    hysteresis (bytes back under ``watermark * rearm_frac`` of the
    budget) and on ``plan_swap`` (a new plan means a new memory
    footprint: the next breach is a fresh edge).

    budget_bytes    the live-bytes allowance.  Defaults to the
                    sampler's own MemConfig budget (budget_gb in the
                    PADDLE_TPU_MEMSTATS grammar) when a config is
                    given; without any budget the monitor is dormant.
    watermark       breach threshold as a fraction of budget (0.9).
    rearm_frac      hysteresis fraction of the firing threshold.
    """

    def __init__(self, budget_bytes=None, config=None, watermark=None,
                 rearm_frac=None):
        if config is not None:
            if budget_bytes is None:
                budget_bytes = config.budget_bytes
            if watermark is None:
                watermark = config.watermark
            if rearm_frac is None:
                rearm_frac = config.rearm_frac
        self.budget_bytes = (None if budget_bytes is None
                             else int(budget_bytes))
        self.watermark = 0.9 if watermark is None else float(watermark)
        self.rearm_frac = 0.7 if rearm_frac is None else float(rearm_frac)
        self._latched = set()
        self.breaches = []              # local record (tests/reports)

    def observe(self, rec, agg):
        kind = rec.get('kind')
        if kind == 'plan_swap':
            # the swapped-in plan reshapes the footprint (that was the
            # point of the re-plan): the next breach is a fresh edge
            self._latched.clear()
            return
        if kind != 'memory_sample' or self.budget_bytes is None:
            return
        observed = rec.get('device_bytes')
        if observed is None:
            return
        threshold = self.budget_bytes * self.watermark
        if 'memory' in self._latched:
            if observed <= threshold * self.rearm_frac:
                self._latched.discard('memory')      # re-arm
            return
        if observed > threshold:
            self._latched.add('memory')
            ev = _emit('memory_pressure',
                       observed_bytes=int(observed),
                       peak_bytes=rec.get('device_peak_bytes'),
                       budget_bytes=self.budget_bytes,
                       watermark=self.watermark,
                       frac=round(observed / self.budget_bytes, 4),
                       source=rec.get('source'))
            self.breaches.append(ev or dict(
                kind='memory_pressure', observed_bytes=int(observed),
                budget_bytes=self.budget_bytes))
