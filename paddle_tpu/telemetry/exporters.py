"""Telemetry exporters: JSONL stream, TensorBoard event files, and the
TensorBoard-style scalar sink.

JsonlWriter is the durable export — one append-only file per host,
rank-tagged records, flushed per line so a preempted worker's stream
is complete up to its last event.  ``tools/run_report.py`` merges
these files across hosts into one run report.

TensorBoardWriter emits NATIVE TensorBoard scalar event files
(``events.out.tfevents.*``) from the same stream — hand-encoded Event
protos in masked-CRC TFRecord framing, pure stdlib (no tensorflow /
tensorboard import).  It consumes only the ``steps`` flushes (the
StepAccumulator's buffered device scalars, already materialized at
the flush boundary) and ``scalar`` records, so selecting it adds
zero per-step host syncs.  Enable with
``telemetry.enable(log_dir, tensorboard=True)`` (TeeWriter fans the
stream to JSONL + TB) then ``tensorboard --logdir <log_dir>``.

ScalarAdapter is the TensorBoard-scalar-shaped sink the hapi VisualDL
callback rewires onto: ``add_scalar(tag, value, step)`` keeps the
legacy ``events.jsonl`` format the old callback wrote (same keys, same
file), and additionally forwards each record to the telemetry recorder
as a ``scalar`` event so the run's scalars live in the same merged
stream as its spans and resilience timeline.
"""
import json
import os
import struct
import threading
import time

from .recorder import get_recorder, _jsonable, _rank

__all__ = ['JsonlWriter', 'ScalarAdapter', 'TensorBoardWriter',
           'TeeWriter']


class JsonlWriter:
    """Append-only JSONL event stream, one file per host process.

    The filename carries the rank (``telemetry-r<rank>.jsonl``) so a
    shared checkpoint/log directory collects every host's stream
    without collisions; each record is additionally rank-tagged for
    merged readers."""

    def __init__(self, directory, rank=None, filename=None):
        self.directory = os.path.abspath(directory)
        self.rank = _rank() if rank is None else rank
        os.makedirs(self.directory, exist_ok=True)
        self.path = os.path.join(
            self.directory, filename or f'telemetry-r{self.rank}.jsonl')
        self._lock = threading.Lock()
        self._fh = open(self.path, 'a')

    def write(self, rec):
        if self._fh is None:
            return
        line = json.dumps(dict(rec, rank=self.rank),
                          default=_jsonable)
        with self._lock:
            if self._fh is None:    # closed while we serialized
                return
            self._fh.write(line + '\n')
            # flush per record: events are boundary-rate, and a
            # preempted worker's stream must be complete on disk
            self._fh.flush()

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None


# -- TensorBoard event files (stdlib-only) ------------------------------------
#
# TB's on-disk format is TFRecord-framed Event protos.  Both layers are
# simple enough to encode by hand — the alternative is a tensorflow /
# tensorboard dependency this image does not ship:
#   TFRecord: u64le(len) · masked_crc32c(len) · data · masked_crc32c(data)
#   Event:    1=wall_time(double) 2=step(int64) 3=file_version(str)
#             5=summary{ 1=value{ 1=tag(str) 2=simple_value(float) } }

_CRC_TABLE = None


def _crc32c(data):
    """CRC-32C (Castagnoli), the TFRecord checksum."""
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (0x82F63B78 if c & 1 else 0)
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _tfrecord(data):
    header = struct.pack('<Q', len(data))
    return (header + struct.pack('<I', _masked_crc(header))
            + data + struct.pack('<I', _masked_crc(data)))


def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_str(field, s):
    data = s.encode('utf-8')
    return bytes([(field << 3) | 2]) + _varint(len(data)) + data


def _pb_msg(field, body):
    return bytes([(field << 3) | 2]) + _varint(len(body)) + body


def _event_proto(wall_time, step=None, tag=None, value=None,
                 file_version=None):
    body = struct.pack('<Bd', 0x09, wall_time)      # 1: wall_time
    if step is not None:
        body += b'\x10' + _varint(max(0, int(step)))  # 2: step
    if file_version is not None:
        body += _pb_str(3, file_version)
    if tag is not None:
        val = _pb_str(1, tag) + struct.pack('<Bf', 0x15, float(value))
        body += _pb_msg(5, _pb_msg(1, val))         # 5: summary.value
    return body


class TensorBoardWriter:
    """Native TensorBoard scalar export over the telemetry stream.

    Attachable wherever JsonlWriter is (``recorder.attach_writer`` /
    ``TeeWriter``): ``write(rec)`` ignores everything except ``steps``
    flushes — each buffered per-step column becomes scalar points
    tagged ``<loop>/<column>`` at the flushed step ids — and
    ``scalar`` records (one point under the record's own tag).  All
    values reaching here were materialized by the flush that produced
    the record, so TB export costs no extra device readback."""

    def __init__(self, directory, rank=None, filename=None):
        self.directory = os.path.abspath(directory)
        self.rank = _rank() if rank is None else rank
        os.makedirs(self.directory, exist_ok=True)
        self.path = os.path.join(
            self.directory, filename
            or f'events.out.tfevents.{int(time.time())}.r{self.rank}')
        self._lock = threading.Lock()
        self._fh = None
        self._closed = False

    def _file(self):
        if self._fh is None:
            self._fh = open(self.path, 'ab')
            if self._fh.tell() == 0:
                self._fh.write(_tfrecord(_event_proto(
                    time.time(), file_version='brain.Event:2')))
        return self._fh

    def _emit(self, points):
        """Write a batch of (tag, value, step, wall_time) points under
        ONE lock/flush — a 32-step flush with several columns is one
        syscall burst, not one per point (JsonlWriter's per-record
        durability contract, at the same boundary)."""
        blobs = []
        for tag, value, step, wall_time in points:
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            blobs.append(_tfrecord(_event_proto(
                wall_time or time.time(), step=step, tag=tag,
                value=v)))
        if not blobs:
            return
        with self._lock:
            if self._closed:
                return
            fh = self._file()
            fh.write(b''.join(blobs))
            fh.flush()

    def add_scalar(self, tag, value, step, wall_time=None):
        self._emit([(tag, value, step, wall_time)])

    def write(self, rec):
        kind = rec.get('kind')
        if kind == 'scalar':
            tag = rec.get('tag', 'scalar')
            self._emit([
                (tag if k == 'value' else f'{tag}/{k}', v,
                 rec.get('step') or 0, rec.get('ts'))
                for k, v in rec.items()
                if k not in ('kind', 'ts', 't', 'rank', 'tag', 'step')
                and isinstance(v, (int, float))])
            return
        if kind != 'steps':
            return
        loop = rec.get('tag', 'train')
        steps = rec.get('step') or []
        ts = rec.get('ts')
        points = []
        for col, vals in rec.items():
            if col in ('kind', 'ts', 't', 'rank', 'tag', 'n', 'step',
                       'step_lo', 'step_hi'):
                continue
            if not isinstance(vals, list):
                continue
            points += [(f'{loop}/{col}', v, steps[i], ts)
                       for i, v in enumerate(vals)
                       if v is not None and i < len(steps)]
        self._emit(points)

    def close(self):
        with self._lock:
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None


class TeeWriter:
    """Fan one telemetry stream out to several writers (JSONL + TB);
    a failing branch never blocks the others."""

    def __init__(self, *writers):
        self.writers = writers

    def write(self, rec):
        for w in self.writers:
            try:
                w.write(rec)
            except Exception:
                pass

    def close(self):
        for w in self.writers:
            try:
                w.close()
            except Exception:
                pass


class ScalarAdapter:
    """TensorBoard-scalar-shaped writer over the telemetry stream.

    Keeps the legacy VisualDL ``events.jsonl`` on disk (same format:
    one JSON object per line with ``tag``/``step``/``ts`` plus metric
    keys) AND emits each record as a telemetry ``scalar`` event, so
    scalars logged through the callback are queryable by
    ``run_report`` next to spans and resilience events."""

    def __init__(self, log_dir, recorder=None):
        self.log_dir = log_dir
        self.rec = recorder or get_recorder()
        self._fh = None
        self._lock = threading.Lock()

    def _file(self):
        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(
                os.path.join(self.log_dir, 'events.jsonl'), 'a')
        return self._fh

    def write_record(self, tag, step, values):
        """Write one already-materialized record: `values` is a dict
        of plain numbers / lists (the CALLER pays any device sync, at
        its own log boundary)."""
        rec = {'tag': tag, 'step': step, 'ts': time.time()}
        rec.update(values)
        with self._lock:
            fh = self._file()
            fh.write(json.dumps(rec, default=_jsonable) + '\n')
            fh.flush()
        self.rec.event('scalar', tag=tag, step=step, **values)
        return rec

    def add_scalar(self, tag, value, step):
        return self.write_record(tag, step, {'value': value})

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None
