"""Telemetry exporters: JSONL stream + TensorBoard-style scalar sink.

JsonlWriter is the durable export — one append-only file per host,
rank-tagged records, flushed per line so a preempted worker's stream
is complete up to its last event.  ``tools/run_report.py`` merges
these files across hosts into one run report.

ScalarAdapter is the TensorBoard-scalar-shaped sink the hapi VisualDL
callback rewires onto: ``add_scalar(tag, value, step)`` keeps the
legacy ``events.jsonl`` format the old callback wrote (same keys, same
file), and additionally forwards each record to the telemetry recorder
as a ``scalar`` event so the run's scalars live in the same merged
stream as its spans and resilience timeline.
"""
import json
import os
import threading
import time

from .recorder import get_recorder, _jsonable, _rank

__all__ = ['JsonlWriter', 'ScalarAdapter']


class JsonlWriter:
    """Append-only JSONL event stream, one file per host process.

    The filename carries the rank (``telemetry-r<rank>.jsonl``) so a
    shared checkpoint/log directory collects every host's stream
    without collisions; each record is additionally rank-tagged for
    merged readers."""

    def __init__(self, directory, rank=None, filename=None):
        self.directory = os.path.abspath(directory)
        self.rank = _rank() if rank is None else rank
        os.makedirs(self.directory, exist_ok=True)
        self.path = os.path.join(
            self.directory, filename or f'telemetry-r{self.rank}.jsonl')
        self._lock = threading.Lock()
        self._fh = open(self.path, 'a')

    def write(self, rec):
        if self._fh is None:
            return
        line = json.dumps(dict(rec, rank=self.rank),
                          default=_jsonable)
        with self._lock:
            if self._fh is None:    # closed while we serialized
                return
            self._fh.write(line + '\n')
            # flush per record: events are boundary-rate, and a
            # preempted worker's stream must be complete on disk
            self._fh.flush()

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None


class ScalarAdapter:
    """TensorBoard-scalar-shaped writer over the telemetry stream.

    Keeps the legacy VisualDL ``events.jsonl`` on disk (same format:
    one JSON object per line with ``tag``/``step``/``ts`` plus metric
    keys) AND emits each record as a telemetry ``scalar`` event, so
    scalars logged through the callback are queryable by
    ``run_report`` next to spans and resilience events."""

    def __init__(self, log_dir, recorder=None):
        self.log_dir = log_dir
        self.rec = recorder or get_recorder()
        self._fh = None
        self._lock = threading.Lock()

    def _file(self):
        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(
                os.path.join(self.log_dir, 'events.jsonl'), 'a')
        return self._fh

    def write_record(self, tag, step, values):
        """Write one already-materialized record: `values` is a dict
        of plain numbers / lists (the CALLER pays any device sync, at
        its own log boundary)."""
        rec = {'tag': tag, 'step': step, 'ts': time.time()}
        rec.update(values)
        with self._lock:
            fh = self._file()
            fh.write(json.dumps(rec, default=_jsonable) + '\n')
            fh.flush()
        self.rec.event('scalar', tag=tag, step=step, **values)
        return rec

    def add_scalar(self, tag, value, step):
        return self.write_record(tag, step, {'value': value})

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None
