"""Memory observatory — the three-source HBM truth plane.

PR-4's liveness walk (:func:`analysis.hlo.peak_memory`) gates planner
candidates against ``--hbm-gb`` and PR-16's supervisor swaps plans on
its say-so, yet nothing ever checked that estimate against what XLA
actually reserves or what devices actually hold live.  This module
closes the memory half of the predicted-vs-observed loop the same way
PR-8 closed the collective-time half, with THREE sources joined
per-module:

* **predicted** — the PR-4 liveness peak, re-derived from the
  compiled module's own HLO text (so prediction and compilation
  describe the same program, not the pre-SPMD trace);
* **compiled** — XLA's ``compiled.memory_analysis()`` (argument /
  output / temp / alias / generated-code bytes), extracted at every
  compile choke point (ParallelTrainer, hapi ``train_batch``,
  ``to_static``, the serving module set, compile-cache warm starts)
  and emitted as one ``memory_compiled`` event per module;
* **live** — a :class:`MemorySampler` thread (default OFF,
  ``PADDLE_TPU_MEMSTATS``, watchdog posture) reading
  ``device.memory_stats()`` on TPU with a ``jax.live_arrays()``
  aval-bytes census fallback on CPU, publishing
  ``memory.device_bytes`` / ``memory.host_rss`` gauges and
  boundary-rate ``memory_sample`` events.

Cost posture — extraction is **free where a Compiled already exists**
(the trainer's ``compiled_text()`` memo, the compile cache's
``aot_compile`` store path) and **armed-only elsewhere**: hapi / jit /
serving choke points and warm-start deserializes pay an extra
``lower().compile()`` per module (measured ~2x one compile, amortized
by the persistent XLA cache when it is on), so they extract only under
``PADDLE_TPU_MEMSTATS``.  The sampler itself never syncs the step
path: ``memory_stats()`` is a host-side read and the live-arrays
census touches only avals — ``bench --mem-smoke`` proves the armed
posture under a device→host transfer guard.

Consumers: ``tools/run_report.py`` renders the per-module three-way
table (predicted/compiled ratio, calibratable like
``collectives_cmp``); :mod:`telemetry.httpd` serves :func:`snapshot`
as ``/memory.json``; :mod:`telemetry.cluster` frames carry the gauges
as per-rank columns; :class:`telemetry.monitors.MemoryMonitor` turns
the live high-water into an exactly-once ``memory_pressure`` edge the
plan supervisor re-plans on (with a tightened budget).
"""
import os
import threading
import time

__all__ = ['MemConfig', 'resolve_memstats', 'armed', 'note_compiled',
           'maybe_note_compiled', 'MemorySampler', 'ensure_sampler',
           'stop_sampler', 'snapshot', 'reset_modules', 'host_rss_bytes',
           'device_memory_stats', 'live_arrays_bytes', 'MEMSTATS_ENV']

MEMSTATS_ENV = 'PADDLE_TPU_MEMSTATS'

_MONO = time.monotonic


class MemConfig:
    """Sampler/monitor knobs, env-parsable like the watchdog Budget.

    interval_s   sampler cadence (seconds; boundary rate, never
                 per-step)
    budget_gb    live-bytes budget the MemoryMonitor fires against
                 (None: the monitor stays dormant — sensing without
                 actuation)
    watermark    fire when device_bytes > budget * watermark
    rearm_frac   re-arm when device_bytes <= budget * watermark *
                 rearm_frac (hysteresis)
    """

    def __init__(self, interval_s=10.0, budget_gb=None, watermark=0.9,
                 rearm_frac=0.7):
        self.interval_s = max(0.05, float(interval_s))
        self.budget_gb = None if budget_gb is None else float(budget_gb)
        self.watermark = float(watermark)
        self.rearm_frac = float(rearm_frac)

    @property
    def budget_bytes(self):
        if self.budget_gb is None:
            return None
        return int(self.budget_gb * (1 << 30))

    @classmethod
    def from_env(cls, text):
        """``PADDLE_TPU_MEMSTATS`` grammar: unset/'0'/'off'/'false' ->
        None; '1'/'on'/'true' -> defaults; else ``k=v,...`` with keys
        interval / budget_gb / watermark / rearm."""
        if text is None:
            return None
        text = text.strip()
        if text.lower() in ('', '0', 'off', 'false', 'no'):
            return None
        if text.lower() in ('1', 'on', 'true', 'yes'):
            return cls()
        keymap = {'interval': 'interval_s', 'interval_s': 'interval_s',
                  'budget_gb': 'budget_gb', 'budget': 'budget_gb',
                  'watermark': 'watermark', 'rearm': 'rearm_frac',
                  'rearm_frac': 'rearm_frac'}
        kwargs = {}
        for part in text.split(','):
            if '=' not in part:
                continue
            k, v = part.split('=', 1)
            k = keymap.get(k.strip())
            if k is None:
                continue
            try:
                kwargs[k] = float(v)
            except ValueError:
                pass
        return cls(**kwargs)

    def to_dict(self):
        return {'interval_s': self.interval_s, 'budget_gb': self.budget_gb,
                'watermark': self.watermark, 'rearm_frac': self.rearm_frac}


def resolve_memstats(arg=None):
    """The shared opt-in posture (same shape as resolve_watchdog):
    explicit False -> None (off even if the env says on); True ->
    MemConfig(); MemConfig/dict pass through; None -> the
    PADDLE_TPU_MEMSTATS env decides.  Returns a MemConfig or None."""
    if arg is False:
        return None
    if arg is None:
        return MemConfig.from_env(os.environ.get(MEMSTATS_ENV))
    if arg is True:
        return MemConfig()
    if isinstance(arg, MemConfig):
        return arg
    if isinstance(arg, dict):
        return MemConfig(**arg)
    raise TypeError(
        f'memstats= expects bool/dict/MemConfig, got {arg!r}')


def armed(arg=None):
    """True when memory extraction at the armed-only choke points
    (hapi/jit/serving/warm-start) should pay its extra compile."""
    return resolve_memstats(arg) is not None


# -- compiled truth -----------------------------------------------------------

# per-module registry behind /memory.json and the live three-way join:
# name -> the memory_compiled event's data dict (newest wins — a
# retrace replaces its module's row)
_modules = {}
_modules_lock = threading.Lock()


def reset_modules():
    """Drop the per-module registry (tests; a fresh run in-process)."""
    with _modules_lock:
        _modules.clear()


def _memory_analysis_fields(compiled):
    """CompiledMemoryStats -> plain byte fields, or None when the
    backend does not implement memory_analysis (older jaxlibs return
    None; some raise)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None
    fields = {}
    for key, attr in (('argument_bytes', 'argument_size_in_bytes'),
                      ('output_bytes', 'output_size_in_bytes'),
                      ('temp_bytes', 'temp_size_in_bytes'),
                      ('alias_bytes', 'alias_size_in_bytes'),
                      ('code_bytes', 'generated_code_size_in_bytes')):
        try:
            fields[key] = int(getattr(mem, attr))
        except Exception:
            fields[key] = 0
    # XLA's own peak reservation: arguments + outputs + temps, minus
    # buffers aliased between them (donation) which exist only once
    fields['compiled_peak_bytes'] = max(
        0, fields['argument_bytes'] + fields['output_bytes']
        + fields['temp_bytes'] - fields['alias_bytes'])
    return fields


def _predicted_peak(compiled, hlo_text=None):
    """The PR-4 liveness estimate over the COMPILED module's own HLO
    text, so predicted and compiled describe the same program."""
    try:
        if hlo_text is None:
            hlo_text = compiled.as_text()
        from ..analysis import hlo as _hlo
        return int(_hlo.peak_memory(_hlo.parse_module(hlo_text)))
    except Exception:
        return None


def note_compiled(name, compiled, *, source='', hlo_text=None,
                  predicted_bytes=None):
    """Extract one Compiled's memory_analysis + liveness prediction
    into a ``memory_compiled`` event and the /memory.json registry.
    FREE for callers that already hold a Compiled; never raises
    (telemetry must not be able to kill a run).  Returns the event
    data dict or None when nothing could be extracted."""
    try:
        fields = _memory_analysis_fields(compiled)
        if fields is None:
            return None
        if predicted_bytes is None:
            predicted_bytes = _predicted_peak(compiled, hlo_text)
        data = dict(name=name, source=source or 'direct', **fields)
        if predicted_bytes is not None:
            data['predicted_peak_bytes'] = int(predicted_bytes)
            if fields['compiled_peak_bytes'] > 0:
                data['ratio'] = round(
                    predicted_bytes / fields['compiled_peak_bytes'], 4)
        with _modules_lock:
            _modules[name] = dict(data)
        from . import event
        event('memory_compiled', **data)
        return data
    except Exception:
        return None


def maybe_note_compiled(name, jitted, example_args, *, source='',
                        memstats=None):
    """The ARMED extraction path for choke points that hold only a
    jitted callable: pays a fresh ``lower().compile()`` (roughly one
    extra compile, amortized by the persistent XLA cache) — so it runs
    only under PADDLE_TPU_MEMSTATS.  Never raises."""
    if not armed(memstats):
        return None
    try:
        compiled = jitted.lower(*example_args).compile()
    except Exception:
        return None
    return note_compiled(name, compiled, source=source or 'armed')


# -- live truth ---------------------------------------------------------------

def host_rss_bytes():
    """Current resident set size of this process (bytes), or None."""
    try:
        with open('/proc/self/statm') as f:
            rss_pages = int(f.read().split()[1])
        return rss_pages * os.sysconf('SC_PAGE_SIZE')
    except Exception:
        pass
    try:
        import resource
        # ru_maxrss is KiB on Linux (bytes on macOS) — high-water, not
        # current, but better than nothing where /proc is absent
        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(ru * 1024)
    except Exception:
        return None


def device_memory_stats():
    """Per-device ``memory_stats()`` rows for the addressable devices,
    or None when the backend does not expose them (CPU does not)."""
    try:
        import jax
        rows = []
        for dev in jax.local_devices():
            st = dev.memory_stats()
            if st is None:
                return None
            rows.append({'device': str(dev.id),
                         'bytes_in_use': int(st.get('bytes_in_use', 0)),
                         'peak_bytes_in_use': int(
                             st.get('peak_bytes_in_use', 0)),
                         'bytes_limit': int(st.get('bytes_limit', 0))})
        return rows or None
    except Exception:
        return None


def live_arrays_bytes():
    """Total committed bytes of all live jax arrays (aval metadata
    only — no device sync, no transfer).  The CPU fallback census so
    tier-1 covers the sampler path on every backend."""
    try:
        import jax
        total = 0
        for a in jax.live_arrays():
            try:
                total += int(a.nbytes)
            except Exception:
                pass
        return total
    except Exception:
        return None


class MemorySampler:
    """Daemon thread publishing live memory truth at boundary rate.

    Each tick reads ``device.memory_stats()`` (TPU/GPU) or falls back
    to the live-arrays census (CPU), sets the
    ``memory.device_bytes`` / ``memory.device_peak_bytes`` /
    ``memory.host_rss`` gauges and emits one ``memory_sample`` event —
    the record :class:`telemetry.monitors.MemoryMonitor` fires
    ``memory_pressure`` from.  Zero per-step work, zero device syncs;
    default OFF (watchdog posture, ``PADDLE_TPU_MEMSTATS``)."""

    def __init__(self, config=None):
        self.config = config if isinstance(config, MemConfig) \
            else (resolve_memstats(config) or MemConfig())
        self._stop = threading.Event()
        self._thread = None
        self.samples = 0            # ticks taken (tests/diagnostics)
        self.last = None            # last sample dict

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name='paddle-tpu-memstats', daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=2.0):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)

    def sample_once(self):
        """Take one sample now (also the thread's tick body)."""
        sample = {'source': None}
        rows = device_memory_stats()
        if rows is not None:
            sample['source'] = 'device_stats'
            sample['device_bytes'] = max(
                r['bytes_in_use'] for r in rows)
            sample['device_peak_bytes'] = max(
                r['peak_bytes_in_use'] for r in rows)
            limit = max(r['bytes_limit'] for r in rows)
            if limit:
                sample['device_limit_bytes'] = limit
        else:
            census = live_arrays_bytes()
            if census is not None:
                sample['source'] = 'live_arrays'
                sample['device_bytes'] = census
                prev = (self.last or {}).get('device_peak_bytes', 0)
                sample['device_peak_bytes'] = max(prev, census)
        rss = host_rss_bytes()
        if rss is not None:
            sample['host_rss'] = rss
        if sample['source'] is None and rss is None:
            return None
        budget = self.config.budget_bytes
        if budget is not None:
            sample['budget_bytes'] = budget
        self.last = sample
        self.samples += 1
        try:
            from . import event, set_gauge
            for key in ('device_bytes', 'device_peak_bytes', 'host_rss'):
                if sample.get(key) is not None:
                    set_gauge(f'memory.{key}', sample[key])
            event('memory_sample', **sample)
        except Exception:
            pass
        return sample

    def _run(self):
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception:
                pass        # the sampler must never kill anything
            self._stop.wait(self.config.interval_s)


# process-global sampler, armed at most once (trainer fit / serving
# engine start call ensure_sampler(); default-off env keeps it None)
_sampler = None
_sampler_lock = threading.Lock()


def ensure_sampler(arg=None):
    """Start the process-global MemorySampler iff the posture says on
    (idempotent; returns the sampler or None).  The cheap call every
    run entry point makes — unset env means this is a no-op."""
    cfg = resolve_memstats(arg)
    if cfg is None:
        return None
    global _sampler
    with _sampler_lock:
        if _sampler is None:
            _sampler = MemorySampler(cfg).start()
        return _sampler


def stop_sampler():
    """Stop and drop the process-global sampler (tests, shutdown)."""
    global _sampler
    with _sampler_lock:
        s, _sampler = _sampler, None
    if s is not None:
        s.stop()
    return s


# -- the three-way join (/memory.json) ----------------------------------------

def snapshot():
    """The /memory.json document: per-module predicted vs compiled
    rows joined with the live gauges.  Plain dict of plain scalars."""
    from .recorder import get_recorder
    with _modules_lock:
        modules = {k: dict(v) for k, v in _modules.items()}
    rec = get_recorder()
    with rec._lock:
        gauges = dict(rec.gauges)
    live = {k.split('.', 1)[1]: v for k, v in gauges.items()
            if k.startswith('memory.')}
    kv = {k: v for k, v in gauges.items()
          if k in ('free_blocks', 'total_blocks', 'kv_occupancy')
          or k.startswith('kv_')}
    cfg = resolve_memstats()
    doc = {'modules': modules, 'live': live, 'kv_pool': kv,
           'armed': cfg is not None}
    if cfg is not None:
        doc['config'] = cfg.to_dict()
    return doc


def prometheus():
    """Prometheus families for the memory plane (the httpd source
    protocol's optional second surface)."""
    doc = snapshot()
    out = []
    for key, val in sorted(doc['live'].items()):
        try:
            out.append(f'# TYPE paddle_tpu_memory_{key} gauge')
            out.append(f'paddle_tpu_memory_{key} {float(val)}')
        except (TypeError, ValueError):
            pass
    for name, row in sorted(doc['modules'].items()):
        for field in ('predicted_peak_bytes', 'compiled_peak_bytes'):
            v = row.get(field)
            if v is None:
                continue
            out.append(f'# TYPE paddle_tpu_memory_{field} gauge')
            esc = str(name).replace('\\', r'\\').replace('"', r'\"')
            out.append(
                f'paddle_tpu_memory_{field}{{module="{esc}"}} {v}')
    return '\n'.join(out) + '\n'
