"""Recorder — the process-global telemetry state.

One Recorder per process holds everything the run emits:

* **events** — typed records (``compile``, ``retrace``,
  ``checkpoint_save``, ``preemption``, ``nan_rollback``,
  ``lint_finding``, ...) appended at host-side boundaries.  The most
  recent ``max_events`` live in a bounded ring — the **flight
  recorder** — that ``dump_flight()`` serializes for post-mortems
  (resilience dumps it next to the checkpoint on SIGTERM preemption,
  NaN rollback and crash).  When a JSONL writer is attached
  (``telemetry.enable``), every event additionally streams to disk.
* **counters / gauges** — cheap monotonic adds and last-value reads
  (retrace counts, dataloader wait seconds, collective bytes).
* **spans** — nested monotonic-clock timers (``span('compile')``);
  each close updates per-name aggregate stats and emits a ``span``
  event.

Emission points are boundary-rate (compile, checkpoint, epoch, flush),
never per-device-step: the per-step path lives in
``stepstats.StepAccumulator`` which buffers DEVICE scalars and reads
them back only every ``flush_interval`` steps, so telemetry never
reintroduces the host syncs the PR-2 lint work removed.

This module imports only stdlib — it must be importable from anywhere
in the package (io, resilience, analysis) without cycles; jax is
touched lazily and only for rank discovery.
"""
import contextlib
import json
import os
import sys
import threading
import time
from collections import deque

__all__ = ['Recorder', 'get_recorder', 'reset', 'hard_off',
           'EVENT_KINDS']

# documented event vocabulary.  Every kind any module under
# paddle_tpu/ emits MUST be declared here — a meta-test greps the
# package's emission sites and fails on an undeclared kind, so the
# vocabulary can no longer drift silently (run_report still groups
# unknown kinds from third-party emitters into the timeline).
EVENT_KINDS = (
    'run_meta',            # enable(): argv / rank / backend
    'compile',             # a step function compiled (dur_s, variants)
    'retrace',             # a compile cache grew past 1 variant
    'checkpoint_save',     # save dispatched (step, async)
    'checkpoint_commit',   # async barrier drained + manifest committed
    'checkpoint_restore',  # restore completed (step, dur_s)
    'checkpoint_quarantine',  # torn dir moved aside
    'commit_intent',       # 2-phase commit: one host's ack landed
    'commit_finalize',     # 2-phase commit: all acks in, manifest up
    'reshape_restore',     # restore resharded onto a different
                           # mesh / process count (elastic reshape)
    'retry',               # resilience.retry re-attempted a transient
                           # failure (fn, attempt, delay_s, error)
    'restart_backoff',     # elastic supervisor delaying a crash
                           # restart (exponential backoff)
    'fault_injected',      # chaos engine injected a planned fault
                           # (seed, fault kind, step/path/op/rank)
    'timeout',             # a collective or step deadline expired
                           # (op/step, budget_s, missing ranks) —
                           # HostCollectives / watchdog emit these
    'straggler',           # a step ran past its soft threshold, or a
                           # peer's heartbeat went stale (rank/peer
                           # attribution)
    'quorum_lost',         # a majority of ranks stopped heartbeating;
                           # the watchdog escalates to abort
    'coordinated_abort',   # the cluster abort flag was raised so
                           # peers stop waiting and restart together
    'preemption',          # SIGTERM/SIGINT latched or observed
    'nan_skip',            # non-finite step skipped on device
    'nan_rollback',        # sentinel demanded a rollback
    'nan_fatal',           # rollback budget exhausted
    'lint_finding',        # analysis finding surfaced at a choke point
    'collectives',         # per-op collective byte census of one step
    'collective_cost',     # predicted wire bytes / torus time per
                           # collective (analysis.costmodel at compile)
    'collective_observed', # profiled per-collective timing from a
                           # capture window (op, wire_bytes, us,
                           # phases) — telemetry.profile emits them,
                           # calibrate_costmodel fits alpha/beta
                           # from them
    'profile_capture',     # one sampled jax.profiler window closed
                           # (step range, trace path, device-compute
                           # vs collective breakdown, error if any)
    'plan_selected',       # auto-sharding planner chose a plan
                           # (winner mesh/assignment, predicted wire
                           # bytes/us + peak HBM, candidates scored)
    'compile_cache',       # persistent compile-cache traffic (action:
                           # hit/miss/serialize/deserialize/quarantine/
                           # warm_start; tier, bytes, dur_s, saved_s)
    'fused_clamp',         # a fused K-chunk exceeded the watchdog
                           # step budget's capacity (requested, fits)
                           # — stage fused_chunk_len() chunks instead
    'serve_step',          # one serving-engine intervention (live
                           # set size, batch bucket, span, decoded
                           # tokens, admissions/evictions/preemptions,
                           # free KV blocks) — serving/engine.py
    'serve_request',       # one serving request finished (rid,
                           # state/reason, prompt_len, tokens, TTFT,
                           # TPOT, preemptions) — deadline breaches
                           # additionally emit a 'timeout' event
    'serve_trace',         # one finished request's full lifecycle
                           # trace (rid + ordered stage rows:
                           # queued -> admitted -> prefill ->
                           # first_token -> decode_span* ->
                           # finished/evicted/preempted, each with
                           # cause and bucket tags) — joinable with
                           # serve_request by rid; telemetry.live
                           # keeps a bounded store of these for the
                           # /requests/<rid> HTTP trace view
    'serve_reject',        # admission control refused a request
                           # (rid, reason: queue_full/draining/
                           # exceeds_pool, retry_after_s, detail) —
                           # the typed load-shedding taxonomy shared
                           # by ServingEngine.submit and the serving
                           # front door (serving/scheduler.py
                           # RejectReason is the one source of truth)
    'fleet_event',         # one serving-fleet control action
                           # (action: dispatch/retry/drain/promote/
                           # replica_down/replica_up, replica, rid) —
                           # serving/router.py's control-plane trail,
                           # joinable with serve_request by rid
    'slo_breach',          # a rolling SLO monitor tripped (what:
                           # ttft_p99 over the watchdog-derived
                           # budget, or deadline-eviction rate over
                           # threshold) — telemetry.monitors emits,
                           # with observed vs budget attribution
    'drift_detected',      # predicted-vs-observed drift: windowed
                           # us_ratio from collective_observed left
                           # its band, or a compile landed after the
                           # run was declared steady — the
                           # re-planning trigger a plan_supervisor
                           # (ROADMAP item 3) consumes
    'straggler_suspect',   # the live cluster view attributed a
                           # straggler (rank + cause: compute/step
                           # skew, behind, stale frame/heartbeat) —
                           # telemetry.monitors latches it off the
                           # ClusterAggregator's joined view; distinct
                           # from the watchdog's own-step 'straggler'
    'rank_divergence',     # cross-rank loss-window spread left its
                           # band: a rank is training on different
                           # state than its peers (corrupt restore,
                           # leaked collective fault, desynced rng)
    'remediation',         # the plan supervisor resolved one incident
                           # (trigger, policy, outcome: swap/hold/
                           # backoff/degraded, with stage + error on
                           # the degrade path) — resilience.supervisor
                           # emits one per debounced incident
    'plan_swap',           # the trainer applied a supervisor-queued
                           # plan at a step/chunk boundary (from_mesh
                           # -> to_mesh, assignment, trigger, dur_s)
                           # — the observe→act loop's actuation edge
    'crash',               # the sys.excepthook crash hook latched an
                           # unhandled exception (ring-only, then the
                           # flight dump persists it)
    'steps',               # StepAccumulator flush (per-step scalars;
                           # fused chunk rows arrive expanded to
                           # per-step entries)
    'span',                # a closed span (name, dur_s)
    'scalar',              # user scalar (VisualDL / ScalarAdapter)
    'flight_dump',         # a flight-recorder dump was written
    'lockcheck',           # analysis.lockcheck disarm summary (locks
                           # wrapped, order-graph edges, cycles,
                           # unguarded accesses, worst hold time) —
                           # one per armed window
    'memory_compiled',     # XLA memory_analysis of one compiled
                           # module (argument/output/temp/alias/code
                           # bytes + the PR-4 liveness prediction and
                           # their ratio) — telemetry.memory extracts
                           # at the compile choke points
    'memory_sample',       # one MemorySampler tick: live device
                           # bytes (memory_stats or the live-arrays
                           # census), high-water, host RSS —
                           # boundary-rate, default OFF
                           # (PADDLE_TPU_MEMSTATS)
    'memory_pressure',     # the live high-water crossed the budget
                           # watermark (telemetry.monitors
                           # MemoryMonitor; latched exactly-once like
                           # slo_breach) — the supervisor re-plans on
                           # it with a tightened hbm budget
    'collective_mismatch',  # the collective flight recorder's
                           # cross-rank ring diff found the first
                           # divergent collective (op/seq/step +
                           # per-rank call sites) — the SPMD-contract
                           # attribution behind a CollectiveTimeout,
                           # straggler escalation, or rank_divergence
)

_WALL = time.time
_MONO = time.perf_counter


def hard_off():
    """True when PADDLE_TPU_TELEMETRY=0/off/false: every telemetry
    entry point becomes a no-op (the escape hatch for runs that cannot
    afford even boundary-rate host bookkeeping)."""
    return os.environ.get('PADDLE_TPU_TELEMETRY', '1').lower() in (
        '0', 'off', 'false')


def _rank():
    """Best-effort host rank; never raises, never initializes a
    backend that is not already up."""
    r = os.environ.get('PADDLE_TRAINER_ID')
    if r is not None:
        try:
            return int(r)
        except ValueError:
            pass
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


class Recorder:
    """Process-global telemetry sink.  Thread-safe; all methods are
    cheap enough for host-loop boundaries (one lock, dict/deque ops).
    Never raises out of an emission path — telemetry must not be able
    to kill a training run."""

    def __init__(self, max_events=2048):
        self._lock = threading.RLock()
        self._events = deque(maxlen=max_events)   # the flight ring
        self.counters = {}
        self.gauges = {}
        self.span_stats = {}    # name -> {count, total_s, max_s}
        self._writer = None     # exporters.JsonlWriter when enabled
        self._subscribers = ()  # in-process stream consumers (live.py)
        self._local = threading.local()
        self._t0_wall = _WALL()
        self._t0 = _MONO()
        self.flush_interval = 32   # StepAccumulator default
        self._step_reservoir = {}  # tag -> bounded list of step dt (s)

    # -- events --------------------------------------------------------------
    def _record(self, kind, data):
        rec = {'kind': kind,
               'ts': round(_WALL(), 6),
               't': round(_MONO() - self._t0, 6)}
        rec.update(data)
        return rec

    def event(self, kind, **data):
        """Append one typed event to the flight ring and (when a
        writer is attached) stream it to JSONL."""
        rec = self._record(kind, data)
        with self._lock:
            self._events.append(rec)
            w = self._writer
            subs = self._subscribers
        if w is not None:
            try:
                w.write(rec)
            except Exception:       # a full disk must not kill a step
                pass
        for cb in subs:
            try:
                cb(rec)
            except Exception:       # a broken consumer must not either
                pass
        return rec

    def event_unlocked(self, kind, **data):
        """Async-signal-safe event: single deque.append (atomic in
        CPython), no lock, no file I/O.  GracefulShutdown's handler
        uses this so a signal landing while another thread holds the
        recorder lock cannot deadlock the latch."""
        rec = self._record(kind, data)
        self._events.append(rec)
        return rec

    def events(self, kind=None):
        with self._lock:
            evs = list(self._events)
        if kind is None:
            return evs
        return [e for e in evs if e['kind'] == kind]

    # -- counters / gauges ---------------------------------------------------
    def add(self, name, n=1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def set_gauge(self, name, value):
        with self._lock:
            self.gauges[name] = value

    # -- spans ---------------------------------------------------------------
    def _span_stack(self):
        stack = getattr(self._local, 'stack', None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextlib.contextmanager
    def span(self, name, **attrs):
        """Nested monotonic timer.  Closing updates span_stats[name]
        and emits a ``span`` event carrying the parent span's name so
        nesting is reconstructable offline."""
        stack = self._span_stack()
        parent = stack[-1] if stack else None
        stack.append(name)
        t0 = _MONO()
        try:
            yield self
        finally:
            dt = _MONO() - t0
            stack.pop()
            with self._lock:
                st = self.span_stats.setdefault(
                    name, {'count': 0, 'total_s': 0.0, 'max_s': 0.0})
                st['count'] += 1
                st['total_s'] += dt
                st['max_s'] = max(st['max_s'], dt)
            ev = dict(attrs)
            if parent:
                ev['parent'] = parent
            self.event('span', name=name, dur_s=round(dt, 6), **ev)

    # -- step-time reservoir -------------------------------------------------
    def observe_step_time(self, dt_s, tag='step', _cap=4096):
        """Record one host-side step duration (seconds) into the
        bounded per-tag reservoir the flight dump summarizes."""
        with self._lock:
            res = self._step_reservoir.setdefault(tag, [])
            res.append(dt_s)
            if len(res) > _cap:
                del res[:len(res) - _cap]

    def step_times(self, tag='step'):
        with self._lock:
            return list(self._step_reservoir.get(tag, []))

    # -- in-process subscribers ----------------------------------------------
    def subscribe(self, callback):
        """Register an in-process consumer of the event stream.  It
        receives exactly the records a writer would — the boundary-rate
        flushes, never anything per-step — after the ring append and
        the JSONL write, outside the recorder lock.  Exceptions are
        swallowed (consumers are observers, never blockers).  Signal-
        safe ``event_unlocked`` records do NOT notify (no user code
        may run in a signal handler's context)."""
        with self._lock:
            if callback not in self._subscribers:
                self._subscribers = self._subscribers + (callback,)
        return callback

    def unsubscribe(self, callback):
        # equality, not identity: a bound method (agg.write) is a
        # fresh object on every attribute access, but compares equal
        with self._lock:
            self._subscribers = tuple(
                cb for cb in self._subscribers if cb != callback)

    # -- writer --------------------------------------------------------------
    def attach_writer(self, writer):
        with self._lock:
            old, self._writer = self._writer, writer
        return old

    @property
    def writer(self):
        return self._writer

    # -- flight dump ---------------------------------------------------------
    def snapshot(self):
        """The flight-recorder document as a plain dict."""
        from .stepstats import percentiles
        with self._lock:
            doc = {
                'version': 1,
                'rank': _rank(),
                'pid': os.getpid(),
                'argv': list(sys.argv),
                'wall_t0': self._t0_wall,
                'counters': dict(self.counters),
                'gauges': {k: _jsonable(v)
                           for k, v in self.gauges.items()},
                'span_stats': {k: dict(v)
                               for k, v in self.span_stats.items()},
                'step_times': {tag: percentiles(ts) for tag, ts in
                               self._step_reservoir.items() if ts},
                'events': [dict(e) for e in self._events],
            }
        return doc

    def dump_flight(self, path):
        """Atomically write the flight-recorder JSON to `path`
        (tmp + rename — a crash mid-dump leaves no torn file).
        Returns the path, or None when the write failed (a dump runs
        inside preemption grace windows; it must never raise)."""
        try:
            doc = self.snapshot()
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = path + '.tmp'
            with open(tmp, 'w') as f:
                json.dump(doc, f, indent=1, default=_jsonable)
            os.replace(tmp, path)
            self.event('flight_dump', path=os.path.abspath(path),
                       n_events=len(doc['events']))
            return path
        except Exception:
            return None


def _jsonable(o):
    """numpy / jax scalars → plain floats for json.dump."""
    try:
        return float(o)
    except (TypeError, ValueError):
        return str(o)


# -- process-global singleton -------------------------------------------------
_recorder = None
_recorder_lock = threading.Lock()


def get_recorder():
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = Recorder()
    return _recorder


def reset():
    """Drop the global recorder (tests; a fresh run in one process).
    Any attached writer is closed first."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None and _recorder.writer is not None:
            try:
                _recorder.writer.close()
            except Exception:
                pass
        _recorder = None
