"""Sampled self-profiling runtime: capture N steps of on-device trace
every M steps, parse it, and close the predicted-vs-observed loop.

PRs 4/6 built a collective cost model and a calibration fitter, but
the fitter's input — ``collective_observed`` telemetry events — had no
producer: predictions rode every run, measurements rode none.  This
module is the producer:

1. **capture** — ``jax.profiler.start_trace``/``stop_trace`` around a
   small window of steps, on a :class:`ProfileSchedule` (default OFF;
   opt in per run with ``fit(profile=…)`` /
   ``ParallelTrainer(profile=…)`` or globally with the
   ``PADDLE_TPU_PROFILE`` env var);
2. **parse** — the emitted perfetto ``*.trace.json.gz`` becomes per-op
   durations (``profiler.trace``, stdlib gzip+json);
3. **match** — profiled collective ops join the compiled module's
   census by instruction name (``analysis.hlo.collective_instrs``:
   opcode + replica-group + byte signature);
4. **emit** — real ``collective_observed`` events (op, wire_bytes,
   phases, us — exactly what ``tools/calibrate_costmodel.py`` fits),
   one ``profile_capture`` event per window, and
   ``profile.*`` gauges splitting per-step device time into compute
   vs collective.

The cost contract: OUTSIDE a window, ``observe()`` is one integer
compare — no host sync, no device traffic (the PR-3 transfer-guard
proof holds with a profiler attached; ``bench.py --profile-smoke``
gates it).  The window close pays one ``block_until_ready`` (the
window's steps must land in the trace) plus host-side parse time.

Schedule spec grammar (env var and string form)::

    PADDLE_TPU_PROFILE=1                      # defaults: 2 steps @ 10,
                                              # every 200, 4 windows
    PADDLE_TPU_PROFILE=every=100,steps=3,start=5,limit=2,dir=/tmp/p
    fit(profile=True) / fit(profile='every=50,steps=2')
    fit(profile={'every': 50, 'steps': 2})
    fit(profile=False)                        # force off, beats env
"""
import contextlib
import os
import time

from . import recorder as _rec

__all__ = ['ProfileSchedule', 'StepProfiler', 'step_profiler',
           'capture', 'resolve_schedule', 'ENV_VAR']

ENV_VAR = 'PADDLE_TPU_PROFILE'

_OFF = ('', '0', 'off', 'false', 'none', 'no')


class ProfileSchedule:
    """When to capture: ``steps``-step windows starting at ``start``
    and every ``every`` steps after, at most ``limit`` windows.
    Windows never include step 0 — the first step of a fresh compile
    measures XLA, not the model."""

    __slots__ = ('every', 'steps', 'start', 'limit', 'dir')

    def __init__(self, every=200, steps=2, start=10, limit=4,
                 dir=None):
        self.every = max(1, int(every))
        self.steps = max(1, int(steps))
        self.start = max(1, int(start))
        self.limit = max(1, int(limit))
        self.dir = dir

    def starts_at(self, step, windows_done=0):
        """True when a capture window should open at `step`."""
        if windows_done >= self.limit or step < self.start:
            return False
        return (step - self.start) % self.every == 0

    def to_dict(self):
        return {'every': self.every, 'steps': self.steps,
                'start': self.start, 'limit': self.limit}

    def __repr__(self):
        return (f'ProfileSchedule(every={self.every}, '
                f'steps={self.steps}, start={self.start}, '
                f'limit={self.limit})')

    @classmethod
    def parse(cls, spec):
        """True / 'on' → defaults; 'k=v,…' / dict → configured;
        off-ish values → None."""
        if spec is None or spec is False:
            return None
        if spec is True:
            return cls()
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, dict):
            return cls(**spec)
        s = str(spec).strip()
        if s.lower() in _OFF:
            return None
        if s.lower() in ('1', 'on', 'true', 'yes'):
            return cls()
        kw = {}
        for part in s.split(','):
            part = part.strip()
            if not part:
                continue
            if '=' not in part:
                raise ValueError(
                    f'bad {ENV_VAR} spec {spec!r}: expected '
                    "'key=value,…' with keys every/steps/start/"
                    'limit/dir')
            k, v = part.split('=', 1)
            k = k.strip()
            if k == 'dir':
                kw[k] = v.strip()
            elif k in ('every', 'steps', 'start', 'limit'):
                kw[k] = int(v)
            else:
                raise ValueError(
                    f'bad {ENV_VAR} key {k!r} in {spec!r}')
        return cls(**kw)

    @classmethod
    def from_env(cls):
        return cls.parse(os.environ.get(ENV_VAR))


def resolve_schedule(profile=None):
    """The schedule a loop should run: an explicit ``profile=`` value
    wins (``False`` forces off); ``None`` defers to the
    ``PADDLE_TPU_PROFILE`` env var — so any run can be profiled
    without a code change.  Returns a ProfileSchedule or None."""
    if profile is None:
        return ProfileSchedule.from_env()
    return ProfileSchedule.parse(profile)


class StepProfiler:
    """Drives sampled capture windows over one step loop.

    Call :meth:`observe` once per step AFTER the step's dispatch,
    passing the step index and (ideally) a device value of that step
    (``sync=loss``) so the window close can wait for the traced work
    to finish.  Call :meth:`close` at loop end — an open window is
    finalized, a pending one abandoned.

    ``hlo_text_fn`` (e.g. ``ParallelTrainer.compiled_text``) enables
    the census join: with it, every profiled collective becomes a
    ``collective_observed`` event carrying wire bytes + phases from
    the compiled module — the calibration fit input.  Without it the
    window still yields the ``profile_capture`` event and the
    compute-vs-collective breakdown gauges.

    Never raises out of observe/close: profiling is evidence, not a
    blocker — a failed capture lands as an ``error`` field on the
    ``profile_capture`` event.
    """

    def __init__(self, schedule, base_dir=None, name='train',
                 hlo_text_fn=None, mesh_shape=None, calibration=None,
                 num_partitions=None):
        self.schedule = schedule
        self.name = name
        self.hlo_text_fn = hlo_text_fn
        self.mesh_shape = dict(mesh_shape) if mesh_shape else None
        self.calibration = calibration
        self.num_partitions = num_partitions
        self.base_dir = base_dir or schedule.dir
        self.windows = []       # summary dict per closed window
        self._active = None     # {'lo': step, 'hi': step, 'dir': …}
        self._last_step = None  # newest step observe() saw
        self._observed_rows = []

    # -- directory -----------------------------------------------------------
    def _ensure_dir(self):
        if self.base_dir is None:
            import tempfile
            self.base_dir = tempfile.mkdtemp(
                prefix='paddle_tpu_profile_')
        os.makedirs(self.base_dir, exist_ok=True)
        return self.base_dir

    # -- loop hooks ----------------------------------------------------------
    def observe(self, step_no, sync=None, span=1):
        """One step just dispatched; `step_no` is its 0-based index in
        THIS loop (both wired loops count calls from 0, so schedule
        steps mean the same thing on every path — and ``start=1``, the
        smallest schedulable window, opens right after the first
        call).  Cheap outside a window (an int compare); opens the
        trace when the NEXT step starts a window, closes + parses when
        this step completed one.

        ``span=K`` (a fused chunk, core.scan_loop) declares that this
        ONE dispatch covered steps ``step_no .. step_no+K-1``: windows
        then open at exact chunk boundaries and close on whole chunks,
        so a window landing inside a fused run attributes its
        collective us to ``step_lo .. step_lo+n*K-1`` — exact step
        ids, never a blurred range."""
        try:
            span = max(1, int(span))
            last = step_no + span - 1
            self._last_step = last
            if self._active is not None:
                if last >= self._active['hi']:
                    # a chunk never splits: the window's hi stretches
                    # to this chunk's exact last step id
                    self._active['hi'] = max(self._active['hi'], last)
                    self._stop(sync)
                return
            # does a scheduled start land inside the NEXT chunk?
            for s in range(last + 1, last + span + 1):
                if self.schedule.starts_at(s, len(self.windows)):
                    # open at the chunk boundary (exact step id) and
                    # cover whole chunks
                    import math
                    n_chunks = math.ceil(self.schedule.steps / span)
                    self._start(last + 1, hi=last + n_chunks * span)
                    break
        except Exception:       # profiling must never kill the loop
            self._active = None

    def close(self, sync=None):
        """Finalize at loop end: an open window is parsed as-is."""
        try:
            if self._active is not None:
                self._stop(sync)
        except Exception:
            self._active = None

    # -- window mechanics ----------------------------------------------------
    def _start(self, lo, hi=None):
        import jax
        d = os.path.join(self._ensure_dir(),
                         f'trace-{self.name}-step{lo:06d}')
        jax.profiler.start_trace(d)
        self._active = {'lo': lo,
                        'hi': (hi if hi is not None
                               else lo + self.schedule.steps - 1),
                        'dir': d, 't0': time.perf_counter()}

    def _stop(self, sync):
        import jax
        win = self._active
        self._active = None
        err = None
        try:
            if sync is not None:
                # the traced steps run async; they must finish before
                # stop_trace or the window would be empty
                jax.block_until_ready(sync)
        except Exception:
            pass
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            err = f'stop_trace: {e!r}'
        # a close() mid-window traced fewer steps than planned — the
        # per-step breakdown must divide by what actually ran; a
        # window whose first step never ran (opened on the loop's
        # final observe) has nothing to parse at all
        ran = self._last_step is None or self._last_step >= win['lo']
        hi = win['hi'] if self._last_step is None \
            else max(win['lo'], min(win['hi'], self._last_step))
        summary = {'window': len(self.windows),
                   'step_lo': win['lo'], 'step_hi': hi,
                   'steps': hi - win['lo'] + 1,
                   'dir': win['dir'], 'name': self.name,
                   'wall_s': round(time.perf_counter() - win['t0'], 4)}
        if err is None and not ran:
            err = 'window opened but no step ran before close()'
        if err is None:
            try:
                self._parse_and_emit(win, summary)
            except Exception as e:
                err = f'parse: {e!r}'
        if err is not None:
            summary['error'] = err
        self.windows.append(summary)
        from . import event as _event
        _event('profile_capture', **summary)

    def _parse_and_emit(self, win, summary):
        from ..profiler import trace as _trace
        files = _trace.find_traces(win['dir'])
        if not files:
            summary['error'] = 'no trace file emitted'
            return
        prof = _trace.parse_trace(files[-1])
        summary['trace'] = files[-1]
        summary.update(prof.summary())
        n_steps = summary['steps']
        devices = self.num_partitions or max(1, prof.device_pids)
        per_step = prof.device_total_us / (n_steps * devices)
        coll_per_step = prof.collective_total_us / (n_steps * devices)
        summary['device_us_per_step'] = round(per_step, 3)
        summary['collective_us_per_step'] = round(coll_per_step, 3)
        summary['collective_frac'] = round(
            coll_per_step / per_step, 4) if per_step else 0.0
        from . import event as _event, set_gauge as _gauge
        # the per-step device-compute vs collective-time breakdown
        _gauge(f'profile.{self.name}.device_us_per_step',
               summary['device_us_per_step'])
        _gauge(f'profile.{self.name}.collective_us_per_step',
               summary['collective_us_per_step'])
        _gauge(f'profile.{self.name}.collective_frac',
               summary['collective_frac'])
        rows = self._match(prof)
        summary['collective_observed'] = len(rows)
        for row in rows:
            self._observed_rows.append(row)
            _event('collective_observed', step_lo=win['lo'],
                   step_hi=win['hi'], **row)

    def _match(self, prof):
        if self.hlo_text_fn is None or not prof.collectives():
            return []
        from ..analysis import hlo as _hlo
        from ..profiler import trace as _trace
        text = self.hlo_text_fn()
        if not text:
            # the loop has no census-joinable module (e.g. a fused-
            # only trainer): keep the window's breakdown, skip the
            # per-instruction join
            return []
        module = _hlo.parse_module(text)
        idx = _hlo.collective_instrs(module,
                                     mesh_shape=self.mesh_shape,
                                     calibration=self.calibration)
        return _trace.match_collectives(
            prof, idx,
            num_partitions=self.num_partitions
            or module.num_partitions,
            name=self.name)

    @property
    def observed(self):
        """All collective_observed rows emitted so far."""
        return list(self._observed_rows)


def step_profiler(profile=None, base_dir=None, name='train', **kw):
    """A StepProfiler for a loop, or None when profiling is off —
    loops guard with ``if prof is not None`` (same contract as
    ``telemetry.step_accumulator``).  ``profile=`` semantics are
    :func:`resolve_schedule`'s; under the telemetry hard kill switch
    (``PADDLE_TPU_TELEMETRY=0``) profiling is off too — there would
    be nowhere to emit the evidence."""
    if _rec.hard_off():
        return None
    sched = resolve_schedule(profile)
    if sched is None:
        return None
    if base_dir is None and sched.dir is None:
        # archive next to the flight-recorder dumps when telemetry
        # has a home; a tempdir otherwise (_ensure_dir)
        from . import flight_dir
        base_dir = flight_dir()
    return StepProfiler(sched, base_dir=base_dir, name=name, **kw)


@contextlib.contextmanager
def capture(trace_dir, name='capture', hlo_text_fn=None,
            mesh_shape=None, calibration=None, num_partitions=None,
            steps=1, sync=None):
    """One-shot capture: trace the body, then parse + match + emit
    (``profile_capture`` + ``collective_observed`` events), yielding
    the profiler so the caller can read ``prof.windows[-1]`` /
    ``prof.observed`` afterwards.  ``steps`` is how many step
    executions the body runs (normalizes the per-step breakdown);
    ``sync`` may be set on the yielded object
    (``cap.sync = loss``) for the close-side block_until_ready."""
    sched = ProfileSchedule(every=1, steps=steps, start=1, limit=1,
                            dir=trace_dir)
    prof = StepProfiler(sched, base_dir=trace_dir, name=name,
                        hlo_text_fn=hlo_text_fn, mesh_shape=mesh_shape,
                        calibration=calibration,
                        num_partitions=num_partitions)
    prof.sync = sync
    prof._start(1)
    try:
        yield prof
    finally:
        prof.close(sync=getattr(prof, 'sync', None))
