"""Sync-free per-step telemetry: StepAccumulator + the unified StepTimer.

The training loops this package instruments are sync-free by design
(PR 2): losses come back as DEVICE scalars and nothing in the hot loop
reads a device value.  Telemetry must not undo that, so per-step
scalars are **buffered as device arrays** and materialized only every
``flush_interval`` steps — by flush time those arrays are
``flush_interval`` steps old and long since computed, so ``np.asarray``
returns without stalling the XLA queue that is busy with the *current*
steps.  The host-side step duration (``perf_counter`` deltas) rides
along for free: it never touches the device at all.

``StepTimer`` is the one step timer of the stack — the near-duplicate
rolling-window timers that used to live in ``paddle_tpu/profiler`` and
``paddle_tpu/utils/profiler`` both re-export this class, which
additionally feeds the telemetry recorder's step-time reservoir so
flight dumps and run reports see timings from every entry point.
"""
import time

from .recorder import get_recorder, hard_off

__all__ = ['StepAccumulator', 'StepTimer', 'percentiles']

_MONO = time.perf_counter


def percentiles(times_s):
    """Summary stats for a list of per-step durations in SECONDS;
    all outputs in milliseconds (the unit step times are read in)."""
    if not times_s:
        return {}
    ts = sorted(times_s)
    n = len(ts)

    def pct(q):
        return ts[min(n - 1, int(n * q))] * 1000.0

    return {'steps': n,
            'mean_ms': sum(ts) / n * 1000.0,
            'p50_ms': pct(0.50),
            'p90_ms': pct(0.90),
            'p99_ms': pct(0.99),
            'max_ms': ts[-1] * 1000.0}


class StepAccumulator:
    """Buffer per-step scalars as device arrays; flush to host every
    ``flush_interval`` steps.

        acc = telemetry.step_accumulator('train')   # None if disabled
        ...
        acc.observe(step=i, step_time_s=dt, wait_s=w, loss=loss)

    ``observe`` does ZERO device reads — device scalars are appended
    verbatim.  ``flush`` (every interval, and once at loop end)
    materializes the buffered columns, emits one ``steps`` event with
    the per-step arrays (step ids, step_time_ms, wait_ms, plus every
    scalar column), and feeds the recorder's step-time reservoir for
    percentile summaries.
    """

    def __init__(self, tag='train', flush_interval=None, recorder=None):
        self.rec = recorder or get_recorder()
        self.tag = tag
        self.flush_interval = max(1, int(
            flush_interval if flush_interval is not None
            else self.rec.flush_interval))
        self._steps = []
        self._times = []
        self._waits = []
        self._scalars = []      # list of {name: device-or-py scalar}
        self._spans = []        # steps each buffered row covers (K>=1)

    def __len__(self):
        return sum(self._spans)

    def observe(self, step=None, step_time_s=None, wait_s=None,
                **scalars):
        """Record one step.  `scalars` values may be device arrays
        (kept lazy) or plain numbers; None values are dropped."""
        self._steps.append(step if step is not None
                           else (self._steps[-1] + self._spans[-1]
                                 if self._steps else 0))
        self._times.append(step_time_s)
        self._waits.append(wait_s)
        self._scalars.append(
            {k: v for k, v in scalars.items() if v is not None})
        self._spans.append(1)
        if len(self) >= self.flush_interval:
            self.flush()

    def observe_chunk(self, step_lo, n, step_time_s=None, wait_s=None,
                      **scalars):
        """Record one fused K-step chunk (core.scan_loop): `scalars`
        values may be K-length stacked DEVICE arrays — kept lazy, like
        observe(), and expanded to per-step rows at flush so run_report
        percentiles stay per-step, not per-chunk.  ``step_time_s`` is
        the chunk's wall time (divided evenly across its steps at
        flush); ``wait_s`` is the chunk's staging wait (attributed to
        the chunk's first step)."""
        n = max(1, int(n))
        self._steps.append(step_lo if step_lo is not None
                           else (self._steps[-1] + self._spans[-1]
                                 if self._steps else 0))
        self._times.append(step_time_s)
        self._waits.append(wait_s)
        self._scalars.append(
            {k: v for k, v in scalars.items() if v is not None})
        self._spans.append(n)
        if len(self) >= self.flush_interval:
            self.flush()

    @staticmethod
    def _expand_scalar(v, n):
        """One buffered scalar cell -> n per-step floats (or Nones).
        The chunk-flush path tolerates K-length stacked arrays: a
        device array of size n contributes one float per step; a plain
        scalar broadcasts."""
        import numpy as np
        try:
            a = np.asarray(v)
            if a.size == n:
                return [float(x) for x in a.reshape(-1)]
            if a.size == 1:
                return [float(a.reshape(()))] * n
        except (TypeError, ValueError):
            pass
        return [None] * n

    def _expand_rows(self, steps, times, waits, rows, spans):
        """Buffered (possibly chunked) rows -> flat per-step columns."""
        f_steps, f_times, f_waits, f_rows = [], [], [], []
        for step, t, w, row, n in zip(steps, times, waits, rows, spans):
            base = step if step is not None else 0
            for j in range(n):
                f_steps.append(base + j)
                f_times.append(t / n if t is not None else None)
                f_waits.append(w if j == 0 else None)
            expanded = {k: self._expand_scalar(v, n)
                        for k, v in row.items()}
            for j in range(n):
                f_rows.append({k: vs[j] for k, vs in expanded.items()
                               if vs[j] is not None})
        return f_steps, f_times, f_waits, f_rows

    def flush(self):
        """Materialize the buffer (the one host read per interval) and
        emit a ``steps`` event.  Safe to call with an empty buffer."""
        if not self._steps:
            return None
        import numpy as np
        steps, times, waits, rows = self._expand_rows(
            self._steps, self._times, self._waits, self._scalars,
            self._spans)
        (self._steps, self._times, self._waits, self._scalars,
         self._spans) = [], [], [], [], []
        cols = {}
        for i, row in enumerate(rows):
            for k, v in row.items():
                try:
                    fv = float(np.asarray(v))
                except (TypeError, ValueError):
                    continue
                cols.setdefault(k, [None] * len(rows))[i] = fv
        ev = {'tag': self.tag, 'n': len(steps),
              'step_lo': steps[0], 'step_hi': steps[-1],
              'step': list(steps)}
        t_ms = [round(t * 1000.0, 4) for t in times if t is not None]
        if t_ms:
            ev['step_time_ms'] = [
                round(t * 1000.0, 4) if t is not None else None
                for t in times]
            for t in times:
                if t is not None:
                    self.rec.observe_step_time(t, tag=self.tag)
        w_ms = [w for w in waits if w is not None]
        if w_ms:
            ev['wait_ms'] = [
                round(w * 1000.0, 4) if w is not None else None
                for w in waits]
            self.rec.add('io.host_wait_s', sum(w_ms))
        ev.update(cols)
        self.rec.add('steps.count', len(steps))
        return self.rec.event('steps', **ev)


class StepTimer:
    """Rolling step-time statistics for training loops — THE step
    timer (``paddle_tpu.profiler.StepTimer`` and
    ``paddle_tpu.utils.profiler.StepTimer`` are this class).

    Blocks on `sync` targets (device arrays) so timings reflect device
    completion, not dispatch.  Unless ``record=False``, every stop()
    also lands in the telemetry recorder's step-time reservoir so the
    flight dump / run report summarize timings from ad-hoc profiling
    loops too."""

    def __init__(self, window=50, record=True, tag='steptimer'):
        self.window = window
        self.tag = tag
        self._record = bool(record) and not hard_off()
        self._times = []
        self._t0 = None

    def start(self):
        self._t0 = _MONO()

    def stop(self, sync=None):
        if sync is not None:
            import jax
            jax.block_until_ready(sync)
        dt = _MONO() - self._t0
        self._times.append(dt)
        if len(self._times) > self.window:
            self._times.pop(0)
        if self._record:
            get_recorder().observe_step_time(dt, tag=self.tag)
        return dt

    @property
    def mean_ms(self):
        if not self._times:
            return 0.0
        return sum(self._times) / len(self._times) * 1000.0

    def summary(self):
        if not self._times:
            return {}
        s = percentiles(self._times)
        # historical key set (profiler.StepTimer callers)
        return {'mean_ms': s['mean_ms'], 'p50_ms': s['p50_ms'],
                'p90_ms': s['p90_ms'], 'max_ms': s['max_ms'],
                'steps': s['steps']}
