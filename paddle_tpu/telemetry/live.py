"""Live observability plane: rolling-window aggregation of the event
stream, readable while the run is still running.

Everything before this module was post-mortem: JSONL streams merged by
``tools/run_report.py`` after the fact.  :class:`LiveAggregator`
subscribes to the SAME boundary-rate Recorder stream the exporters
consume (``Recorder.subscribe`` — no new sync points, no per-step host
work, nothing touches a device array) and maintains:

* **sliding-window percentiles** — TTFT / TPOT (from ``serve_request``
  events), serving-intervention time (``serve_step.dur_s``) and
  train-step time (``steps`` flushes), over a wall-clock window
  (default 60s) so the numbers describe *now*, not the whole run;
* **rate-derived counters** — decoded tokens/s, admissions,
  evictions *by cause*, preemptions, and compile events in steady
  state (a compile after ``mark_steady()`` is a bucket-set leak —
  the drift monitor turns it into a ``drift_detected`` event);
* **live gauges** — KV-pool block occupancy, queue depth, active
  lanes, free blocks: the last ``serve_step``'s snapshot fields;
* a bounded **per-request trace store** — ``serve_trace`` events
  (one per finished request, the whole queued→prefill→decode→finish
  lifecycle) keyed by rid for the ``/requests/<rid>`` HTTP view,
  plus a live-trace hook an attached engine provides for requests
  still in flight;
* recent **alerts** — ``slo_breach`` / ``drift_detected`` events from
  ``telemetry.monitors``, surfaced in ``/status.json``.

Consumers: :class:`telemetry.httpd.MetricsServer` renders
``snapshot()`` as ``/status.json`` and ``prometheus()`` as
``/metrics``; ``telemetry.monitors`` attaches SLO/drift monitors that
observe the same routed records.  The aggregator itself emits nothing
and syncs nothing — attaching it to a training loop is free (proven by
the transfer-guard test and ``bench.py --obs-smoke``).

Thread-safety: one RLock around all state; the HTTP server's scrape
threads read snapshots while the engine thread routes events.  A
monitor emitting an alert from inside ``write()`` re-enters the
recorder → subscriber path; the RLock plus kind-routing (alert kinds
only land in the alert ring) keeps that re-entrancy shallow and
deadlock-free.
"""
import threading
import time
from collections import OrderedDict, deque

from .recorder import get_recorder

__all__ = ['RollingWindow', 'RateCounter', 'LiveAggregator']

_MONO = time.monotonic


class RollingWindow:
    """Wall-clock-bounded sample reservoir: percentiles over the last
    ``window_s`` seconds (bounded at ``cap`` samples either way)."""

    def __init__(self, window_s=60.0, cap=4096):
        self.window_s = float(window_s)
        self._samples = deque(maxlen=int(cap))   # (t_mono, value)

    def add(self, value, now=None):
        if value is None:
            return
        self._samples.append(
            (now if now is not None else _MONO(), float(value)))

    def _evict(self, now):
        cutoff = now - self.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def values(self, now=None):
        self._evict(now if now is not None else _MONO())
        return [v for _, v in self._samples]

    def percentiles(self, now=None):
        """{count, mean, p50, p90, p99, max} or {} when empty."""
        vals = sorted(self.values(now))
        if not vals:
            return {}
        n = len(vals)

        def pct(q):
            return vals[min(n - 1, int(n * q))]

        return {'count': n, 'mean': sum(vals) / n,
                'p50': pct(0.50), 'p90': pct(0.90), 'p99': pct(0.99),
                'max': vals[-1]}


class RateCounter:
    """Monotonic total plus an events-per-second rate over the rolling
    window (increments timestamped, old ones evicted on read)."""

    def __init__(self, window_s=60.0, cap=4096):
        self.window_s = float(window_s)
        self.total = 0.0
        self._t0 = _MONO()
        self._incs = deque(maxlen=int(cap))      # (t_mono, n)

    def add(self, n=1, now=None):
        if not n:
            return
        self.total += n
        self._incs.append((now if now is not None else _MONO(),
                           float(n)))

    def rate(self, now=None):
        """Increments per second over the window (0.0 when idle)."""
        now = now if now is not None else _MONO()
        cutoff = now - self.window_s
        while self._incs and self._incs[0][0] < cutoff:
            self._incs.popleft()
        if not self._incs:
            return 0.0
        # divide by the full window once it has elapsed, by the
        # counter's age before that (a 5s-old run is not averaged
        # down over 60s of nonexistent history, and one fresh
        # increment cannot divide by a near-zero span)
        span = min(self.window_s, max(1e-3, now - self._t0))
        return sum(n for _, n in self._incs) / span

    def windowed(self, now=None):
        """Sum of increments inside the window."""
        now = now if now is not None else _MONO()
        cutoff = now - self.window_s
        while self._incs and self._incs[0][0] < cutoff:
            self._incs.popleft()
        return sum(n for _, n in self._incs)


class LiveAggregator:
    """The live rolling view over one process's telemetry stream.

        agg = LiveAggregator().install()
        ...run...
        agg.snapshot()          # /status.json
        agg.prometheus()        # /metrics text
        agg.uninstall()

    ``install()`` subscribes to the process-global Recorder;
    ``write(rec)`` is also a valid exporter-shaped entry point so the
    aggregator can sit in a TeeWriter if a caller prefers.  Attached
    monitors (``telemetry.monitors``) observe every routed record
    after the aggregator's own state update.
    """

    def __init__(self, window_s=60.0, max_traces=256, max_alerts=64):
        self.window_s = float(window_s)
        self._lock = threading.RLock()
        # write() is a subscriber callback — it runs on whatever
        # thread emits (trainer, serving engine, supervisor worker)
        # while scrape threads call snapshot(); every mutable field
        # below is therefore guarded by _lock.
        self._recorder = None           # guarded-by: _lock
        self._t0 = _MONO()
        self.monitors = []              # guarded-by: _lock
        self._in_write = threading.local()
        # serving latency windows (seconds)
        self.ttft = RollingWindow(window_s)          # guarded-by: _lock
        self.tpot = RollingWindow(window_s)          # guarded-by: _lock
        self.intervention_s = RollingWindow(window_s)  # guarded-by: _lock
        self.step_ms = {}  # loop tag -> RollingWindow  # guarded-by: _lock
        # rates / totals.  Tokens are two MONOTONIC counters (emitted
        # and preemption-discarded) rather than one net counter: the
        # Prometheus families must never decrease (a dropping counter
        # reads as a reset and corrupts rate() queries), while the
        # delivered figure (emitted - discarded) stays exact.
        self.tokens_emitted = RateCounter(window_s)    # guarded-by: _lock
        self.tokens_discarded = RateCounter(window_s)  # guarded-by: _lock
        self.admitted = RateCounter(window_s)          # guarded-by: _lock
        self.finished = RateCounter(window_s)          # guarded-by: _lock
        self.preempted = RateCounter(window_s)         # guarded-by: _lock
        self.compiles = RateCounter(window_s)          # guarded-by: _lock
        self.by_cause = {}  # finish cause -> RateCounter  # guarded-by: _lock
        self.requests_seen = 0          # guarded-by: _lock
        self.steady_since = None  # mono ts of mark_steady()  # guarded-by: _lock
        self.compiles_after_steady = 0  # guarded-by: _lock
        # live gauges (last serve_step snapshot)
        self.gauges = {}                # guarded-by: _lock
        self._last_serve_step_t = None  # guarded-by: _lock
        # bounded stores
        self._traces = OrderedDict()  # rid -> trace rows (LRU)  # guarded-by: _lock
        self._max_traces = int(max_traces)
        self.alerts = deque(maxlen=int(max_alerts))    # guarded-by: _lock
        self.live_trace_fn = None  # engine hook: rid -> rows|None  # guarded-by: _lock

    # -- lifecycle -----------------------------------------------------------
    def install(self, recorder=None):
        """Subscribe to the (given or global) Recorder's stream."""
        rec = recorder or get_recorder()
        # claim the slot under _lock: an unlocked check-then-act here
        # let two install() racers both subscribe, double-counting
        # every event thereafter
        with self._lock:
            if self._recorder is not None:
                return self
            self._recorder = rec
        rec.subscribe(self.write)
        return self

    def uninstall(self):
        with self._lock:
            rec, self._recorder = self._recorder, None
        if rec is not None:
            rec.unsubscribe(self.write)
        return self

    def attach_monitor(self, monitor):
        with self._lock:
            self.monitors.append(monitor)
        return monitor

    def mark_steady(self, now=None):
        """Declare warmup over: compiles from here on are anomalies
        (the drift monitor's post-warmup compile detector keys off
        this, and ``compiles_after_steady`` counts them)."""
        with self._lock:
            self.steady_since = now if now is not None else _MONO()

    # -- stream consumption ---------------------------------------------------
    def write(self, rec):
        """Route one event record (exporter-shaped entry point)."""
        if getattr(self._in_write, 'depth', 0) > 2:
            return          # a monitor's alert re-entered; stop here
        self._in_write.depth = getattr(self._in_write, 'depth', 0) + 1
        try:
            kind = rec.get('kind')
            now = _MONO()
            # monitors run UNDER the lock too: they read (and, via
            # window eviction, mutate) the same deques a scrape
            # thread's snapshot() iterates — the RLock keeps their
            # re-entrant alert emission on this thread legal while
            # excluding concurrent readers
            with self._lock:
                handler = self._HANDLERS.get(kind)
                if handler is not None:
                    handler(self, rec, now)
                for m in self.monitors:
                    try:
                        m.observe(rec, self)
                    except Exception:
                        pass    # a monitor must never block the run
        finally:
            self._in_write.depth -= 1

    def close(self):                # writer-protocol compatibility
        self.uninstall()

    # per-kind state updates (called under self._lock)
    def _on_serve_step(self, rec, now):  # locked-by: _lock
        dur = rec.get('dur_s')
        if dur is not None:
            self.intervention_s.add(dur, now)
        # decoded span tokens + the prefill first tokens this event
        # carries forward; discarded (preemption rollback) tracked
        # separately so delivered = emitted - discarded matches the
        # engine's accounting without any counter ever decreasing
        self.tokens_emitted.add((rec.get('decoded') or 0)
                                + (rec.get('prefilled') or 0), now)
        self.tokens_discarded.add(rec.get('discarded') or 0, now)
        self.admitted.add(rec.get('admitted') or 0, now)
        self.preempted.add(rec.get('preempted') or 0, now)
        for k in ('live', 'batch', 'span', 'queued', 'free_blocks',
                  'total_blocks', 'intervention', 'kv_frag_frac',
                  'kv_largest_free_run', 'kv_high_water'):
            if rec.get(k) is not None:
                self.gauges[k] = rec[k]
        free = rec.get('free_blocks')
        total = rec.get('total_blocks')
        if free is not None and total:
            # usable pool excludes the reserved trash block
            usable = max(1, total - 1)
            self.gauges['kv_occupancy'] = round(
                (usable - free) / usable, 4)
        self._last_serve_step_t = now

    def _on_serve_request(self, rec, now):  # locked-by: _lock
        self.requests_seen += 1
        self.finished.add(1, now)
        self.ttft.add(rec.get('ttft_s'), now)
        self.tpot.add(rec.get('tpot_s'), now)
        reason = rec.get('reason') or '?'
        self.by_cause.setdefault(
            reason, RateCounter(self.window_s)).add(1, now)

    def _on_serve_trace(self, rec, now):  # locked-by: _lock
        rid = rec.get('rid')
        if rid is None:
            return
        self._traces[rid] = rec.get('trace') or []
        self._traces.move_to_end(rid)
        while len(self._traces) > self._max_traces:
            self._traces.popitem(last=False)

    def _on_steps(self, rec, now):  # locked-by: _lock
        tag = rec.get('tag', 'train')
        win = self.step_ms.setdefault(tag, RollingWindow(self.window_s))
        for t in rec.get('step_time_ms') or ():
            if t is not None:
                win.add(t, now)

    def _on_compile(self, rec, now):  # locked-by: _lock
        self.compiles.add(1, now)
        if self.steady_since is not None:
            self.compiles_after_steady += 1

    def _on_alert(self, rec, now):  # locked-by: _lock
        self.alerts.append(dict(rec))

    _HANDLERS = {
        'serve_step': _on_serve_step,
        'serve_request': _on_serve_request,
        'serve_trace': _on_serve_trace,
        'steps': _on_steps,
        'compile': _on_compile,
        'slo_breach': _on_alert,
        'drift_detected': _on_alert,
        # cluster-plane edges (telemetry.cluster monitors) belong in
        # the same alert ring /status.json surfaces
        'straggler_suspect': _on_alert,
        'rank_divergence': _on_alert,
        # the memory observatory's actuation edge (MemoryMonitor)
        'memory_pressure': _on_alert,
    }

    # -- reads ---------------------------------------------------------------
    def request_trace(self, rid):
        """The stored (finished) trace for `rid`, or — via the engine
        hook — the live one; None when unknown."""
        with self._lock:
            rows = self._traces.get(rid)
            live_fn = self.live_trace_fn
        if rows is not None:
            return {'rid': rid, 'state': 'finished', 'trace': rows}
        if live_fn is not None:
            try:
                live = live_fn(rid)
            except Exception:
                live = None
            if live is not None:
                return {'rid': rid, 'state': 'live', 'trace': live}
        return None

    def snapshot(self, now=None):
        """The /status.json document: every window summarized at one
        instant.  Plain dict of plain scalars — json.dumps-able."""
        now = now if now is not None else _MONO()
        with self._lock:
            def ms(p):
                return {k: (round(v * 1000.0, 3)
                            if k != 'count' else v)
                        for k, v in p.items()}

            doc = {
                'uptime_s': round(now - self._t0, 3),
                'window_s': self.window_s,
                'serving': {
                    'ttft_ms': ms(self.ttft.percentiles(now)),
                    'tpot_ms': ms(self.tpot.percentiles(now)),
                    'intervention_ms': ms(
                        self.intervention_s.percentiles(now)),
                    'tokens_per_s': round(
                        self.tokens_emitted.rate(now)
                        - self.tokens_discarded.rate(now), 3),
                    'decoded_tokens': int(self.tokens_emitted.total
                                          - self.tokens_discarded.total),
                    'tokens_emitted': int(self.tokens_emitted.total),
                    'tokens_discarded': int(
                        self.tokens_discarded.total),
                    'requests_finished': self.requests_seen,
                    'admitted': int(self.admitted.total),
                    'admit_rate': round(self.admitted.rate(now), 3),
                    'preempted': int(self.preempted.total),
                    # ALL finish causes; 'eos'/'max_tokens' are clean
                    # completions, everything else is an eviction
                    'finished_by_cause': {
                        c: int(r.total)
                        for c, r in sorted(self.by_cause.items())},
                    'gauges': dict(self.gauges),
                },
                'steps': {tag: {k: round(v, 3) if k != 'count' else v
                                for k, v in
                                win.percentiles(now).items()}
                          for tag, win in self.step_ms.items()},
                'compiles': {
                    'total': int(self.compiles.total),
                    'steady': self.steady_since is not None,
                    'after_steady': self.compiles_after_steady,
                },
                'alerts': [dict(a) for a in self.alerts],
                'traced_requests': list(self._traces),
            }
        return doc

    def prometheus(self, now=None):
        """The /metrics document: Prometheus text exposition format
        (one HELP/TYPE pair per family, ``paddle_tpu_`` prefix)."""
        now = now if now is not None else _MONO()
        snap = self.snapshot(now)
        out = []

        def esc(v):
            # exposition-format label escaping: a caller-chosen loop
            # tag containing " \ or a newline must not invalidate the
            # whole scrape
            return str(v).replace('\\', r'\\').replace('"', r'\"') \
                .replace('\n', r'\n')

        def fam(name, mtype, help_, rows):
            emitted = False
            for labels, value in rows:
                if value is None:
                    continue
                if not emitted:
                    out.append(f'# HELP paddle_tpu_{name} {help_}')
                    out.append(f'# TYPE paddle_tpu_{name} {mtype}')
                    emitted = True
                lbl = ('{' + ','.join(f'{k}="{esc(v)}"' for k, v in
                                      sorted(labels.items())) + '}'
                       ) if labels else ''
                out.append(f'paddle_tpu_{name}{lbl} {value}')

        srv = snap['serving']
        for metric, help_ in (('ttft_ms', 'time to first token (ms), '
                                          'rolling window'),
                              ('tpot_ms', 'time per output token (ms), '
                                          'rolling window'),
                              ('intervention_ms',
                               'serving intervention wall time (ms), '
                               'rolling window')):
            pct = srv[metric]
            fam(f'serve_{metric}', 'gauge', help_,
                [({'quantile': q}, pct.get(q))
                 for q in ('p50', 'p90', 'p99')]
                + [({'quantile': 'mean'}, pct.get('mean'))])
        fam('serve_tokens_per_s', 'gauge',
            'delivered tokens per second, rolling window',
            [({}, srv['tokens_per_s'])])
        fam('serve_tokens_emitted_total', 'counter',
            'tokens emitted since engine start (monotonic)',
            [({}, srv['tokens_emitted'])])
        fam('serve_tokens_discarded_total', 'counter',
            'preemption-discarded tokens since engine start '
            '(monotonic; delivered = emitted - discarded)',
            [({}, srv['tokens_discarded'])])
        fam('serve_delivered_tokens', 'gauge',
            'delivered tokens since engine start '
            '(emitted - discarded)',
            [({}, srv['decoded_tokens'])])
        fam('serve_requests_finished_total', 'counter',
            'requests finished (any cause)',
            [({}, srv['requests_finished'])])
        fam('serve_admitted_total', 'counter', 'requests admitted',
            [({}, srv['admitted'])])
        fam('serve_preempted_total', 'counter',
            'pool-pressure preemptions', [({}, srv['preempted'])])
        fam('serve_finished_total', 'counter',
            'finished requests by cause (incl. clean completions)',
            [({'cause': c}, n)
             for c, n in srv['finished_by_cause'].items()])
        fam('serve_evictions_total', 'counter',
            'EVICTED requests by cause (clean eos/max_tokens '
            'completions excluded — alertable)',
            [({'cause': c}, n)
             for c, n in srv['finished_by_cause'].items()
             if c not in ('eos', 'max_tokens')])
        g = srv['gauges']
        fam('serve_kv_occupancy', 'gauge',
            'KV pool block occupancy fraction (0-1)',
            [({}, g.get('kv_occupancy'))])
        fam('serve_free_blocks', 'gauge', 'free KV pool blocks',
            [({}, g.get('free_blocks'))])
        fam('serve_kv_frag_frac', 'gauge',
            'KV pool fragmentation (1 - largest free run / free)',
            [({}, g.get('kv_frag_frac'))])
        fam('serve_kv_largest_free_run', 'gauge',
            'largest contiguous free KV block run',
            [({}, g.get('kv_largest_free_run'))])
        fam('serve_kv_high_water_blocks', 'gauge',
            'lifetime peak of simultaneously owned KV blocks',
            [({}, g.get('kv_high_water'))])
        fam('serve_queue_depth', 'gauge', 'queued requests',
            [({}, g.get('queued'))])
        fam('serve_active_lanes', 'gauge', 'live decode lanes',
            [({}, g.get('live'))])
        fam('serve_batch_bucket', 'gauge',
            'current padded decode batch bucket',
            [({}, g.get('batch'))])
        for tag, pct in snap['steps'].items():
            fam('step_time_ms', 'gauge',
                'host step time (ms), rolling window',
                [({'loop': tag, 'quantile': q}, pct.get(q))
                 for q in ('p50', 'p90', 'p99')])
        fam('compiles_total', 'counter', 'compile events observed',
            [({}, snap['compiles']['total'])])
        fam('compiles_after_steady_total', 'counter',
            'compiles after the run was declared steady',
            [({}, snap['compiles']['after_steady'])])
        alerts = {}
        for a in snap['alerts']:
            alerts[a.get('kind', '?')] = \
                alerts.get(a.get('kind', '?'), 0) + 1
        fam('alerts_total', 'counter',
            'slo_breach / drift_detected alerts in the ring',
            [({'kind': k}, n) for k, n in sorted(alerts.items())])
        fam('uptime_seconds', 'gauge', 'aggregator uptime',
            [({}, snap['uptime_s'])])
        return '\n'.join(out) + '\n'
