"""jaxpr-level lint rules + the rule registry.

Each rule is a generator ``rule(ctx) -> yields Finding`` registered
under a stable kebab-case id (the id users put in suppression comments
and ``disable=`` lists).  Rules only READ the traced jaxpr — no device
execution — and every finding carries the best source location jax's
source_info gives us.

Shipped rules
-------------
recompile-hazard   Python scalars / weak-typed leaves in the step
                   signature, and shapes that vary across observed
                   signatures: each variant is a full XLA recompile.
host-sync          host callbacks compiled into the step
                   (pure_callback/io_callback — an XLA→host round trip
                   per step; debug_callback reported as info).
replicated-giant   constant-derived intermediates above a byte
                   threshold with no sharding constraint while a Mesh
                   is active: XLA materializes them replicated on
                   EVERY device.
amp-promotion      matmul/conv operands upcast bf16→f32 before the
                   dot (the MXU then runs the slow f32 path — use
                   preferred_element_type) and non-weak f32 constants
                   that drag bf16 intermediates up to f32.
donation-violation donated buffers with no same-shape/dtype output to
                   alias: XLA frees them, the caller's arrays die, and
                   the donation saves nothing.
constant-capture   large arrays baked into the jaxpr as consts —
                   recompiled per value and replicated into the
                   module instead of fed as arguments.
chunk-break        host callbacks/syncs inside a step audited in its
                   FUSED posture (fused_steps=K, core.scan_loop):
                   each K-chunk would round-trip to the host K times
                   from inside one dispatch.  Silent unless the lint
                   caller declares fused intent.
"""
import numpy as np

import jax.numpy as jnp

from . import walker
from .findings import Finding, HIGH, WARN, INFO

__all__ = ['RULES', 'register_rule', 'RuleContext', 'DEFAULT_THRESHOLDS',
           'run_rules']

DEFAULT_THRESHOLDS = {
    # replicated-giant: bytes of a constant-derived unsharded
    # intermediate under an active mesh (64 MiB ≈ a [4096, 4096] f32)
    'replicated_bytes': 64 << 20,
    # constant-capture: bytes of a captured const worth flagging /
    # escalating to high severity
    'const_bytes': 1 << 20,
    'const_bytes_high': 128 << 20,
}

_LOW_PRECISION = (jnp.bfloat16, jnp.float16)


class RuleContext:
    """Everything a rule may inspect for one lint run."""

    def __init__(self, closed, *, mesh=None, donate_argnums=(),
                 arg_leaf_ranges=None, python_scalars=None,
                 signatures=None, thresholds=None, name=None,
                 fused_steps=None):
        self.closed = closed                  # ClosedJaxpr
        self.jaxpr = closed.jaxpr
        self.consts = closed.consts
        self.mesh = mesh
        self.donate_argnums = tuple(donate_argnums or ())
        # [(start, stop)] flat-invar index range of each example arg
        self.arg_leaf_ranges = arg_leaf_ranges or []
        # [(arg_index, value)] example args passed as Python scalars
        self.python_scalars = python_scalars or []
        # optional [(shape-tuple, ...)] per observed call signature
        self.signatures = signatures
        self.thresholds = dict(DEFAULT_THRESHOLDS)
        self.thresholds.update(thresholds or {})
        self.name = name
        # chunk length when the step is audited in its fused posture
        # (core.scan_loop); None/0 keeps the chunk-break rule silent
        self.fused_steps = fused_steps

    def walk(self):
        return walker.walk(self.jaxpr)

    def producer_map(self):
        """var -> producing eqn over the whole (nested) jaxpr."""
        prod = {}
        for _, eqn in self.walk():
            for ov in eqn.outvars:
                prod[ov] = eqn
        return prod

    def arg_of_invar(self, invar_index):
        for argpos, (start, stop) in enumerate(self.arg_leaf_ranges):
            if start <= invar_index < stop:
                return argpos
        return None


RULES = {}


def register_rule(rule_id, severity):
    """Register ``fn(ctx) -> iterable[Finding]`` under `rule_id`.
    `severity` documents the rule's default level (rules may yield
    other levels for sub-cases)."""
    def deco(fn):
        RULES[rule_id] = (severity, fn)
        fn.rule_id = rule_id
        return fn
    return deco


def run_rules(ctx, disable=()):
    out = []
    for rule_id, (_, fn) in RULES.items():
        if rule_id in disable:
            continue
        out.extend(fn(ctx))
    return out


def _loc(eqn):
    return walker.eqn_location(eqn)


def _fmt_aval(aval):
    try:
        return aval.str_short()
    except Exception:
        return str(aval)


# -- recompile-hazard ---------------------------------------------------------

def scalar_arg_findings(python_scalars, name=None):
    """The shared Python-scalar-in-signature findings — used by the
    jaxpr rule (ctx.python_scalars) AND by to_static(check=) for the
    scalars its own cache closes over as static values.  ONE place
    owns the severity mapping (float: unbounded values, HIGH; int:
    usually bounded sizes, WARN; bool: two variants at most, INFO)."""
    for argpos, val in python_scalars:
        kind = type(val).__name__
        sev = HIGH if isinstance(val, float) else \
            (INFO if isinstance(val, bool) else WARN)
        yield Finding(
            'recompile-hazard', sev,
            f'argument {argpos} of {name or "the step"} is a Python '
            f'{kind} ({val!r}): jit treats it as a static constant, so '
            'every distinct value triggers a full retrace + XLA '
            'recompile. Pass it as a jnp/np array (traced) or mark it '
            'static deliberately.',
            origin='jaxpr')


@register_rule('recompile-hazard', HIGH)
def recompile_hazard(ctx):
    """Step-signature elements that fork the jit cache."""
    yield from scalar_arg_findings(ctx.python_scalars, ctx.name)
    scalar_args = {i for i, _ in ctx.python_scalars}
    for i, invar in enumerate(ctx.jaxpr.invars):
        aval = getattr(invar, 'aval', None)
        if aval is not None and getattr(aval, 'weak_type', False):
            argpos = ctx.arg_of_invar(i)
            if argpos in scalar_args:
                continue    # already reported as a Python scalar
            where = f'argument {argpos}' if argpos is not None \
                else f'input leaf {i}'
            yield Finding(
                'recompile-hazard', WARN,
                f'{where} is a weak-typed {_fmt_aval(aval)} leaf: '
                'weak/strong dtype mismatches fork the jit cache '
                '(one compile per flavor). Build it with an explicit '
                'dtype, e.g. jnp.asarray(x, jnp.float32).',
                origin='jaxpr')
    if ctx.signatures and len(ctx.signatures) > 1:
        arities = {len(s) for s in ctx.signatures}
        if len(arities) == 1:
            n = arities.pop()
            for argpos in range(n):
                shapes = {tuple(s[argpos]) for s in ctx.signatures}
                if len(shapes) > 1:
                    pretty = sorted(shapes)[:4]
                    yield Finding(
                        'recompile-hazard', HIGH,
                        f'argument {argpos} shape varies across observed '
                        f'step signatures ({pretty}{"..." if len(shapes) > 4 else ""}): '
                        'each new shape is a full recompile. Pad or '
                        'bucket batches to a fixed set of shapes '
                        '(drop_last=True for ragged final batches).',
                        origin='jaxpr')


# -- host-sync ----------------------------------------------------------------

_SYNC_PRIMS = {'pure_callback': HIGH, 'io_callback': HIGH,
               'debug_callback': INFO}


@register_rule('host-sync', HIGH)
def host_sync(ctx):
    """Host callbacks compiled into the step."""
    for _, eqn in ctx.walk():
        sev = _SYNC_PRIMS.get(eqn.primitive.name)
        if sev is None:
            continue
        f, l = _loc(eqn)
        if eqn.primitive.name == 'debug_callback':
            msg = ('debug callback inside the compiled step: it runs '
                   'on the host each execution — fine for debugging, '
                   'remove for production steps.')
        else:
            msg = (f'{eqn.primitive.name} inside the compiled step: '
                   'XLA stalls the device and round-trips to the host '
                   'on EVERY step. Move the host work to epoch/log '
                   'boundaries or express it in jnp.')
        yield Finding('host-sync', sev, msg, file=f, line=l,
                      origin='jaxpr')


# -- chunk-break --------------------------------------------------------------

_CHUNK_BREAKERS = {'pure_callback': HIGH, 'io_callback': HIGH,
                   'debug_callback': WARN, 'infeed': WARN,
                   'outfeed': WARN}


@register_rule('chunk-break', WARN)
def chunk_break(ctx):
    """Host round-trips inside a step audited in its FUSED posture
    (``fused_steps=K``, core.scan_loop).  A per-step host callback is
    merely slow; inside a K-step ``lax.scan`` it fires K times per
    dispatch and serializes the whole chunk on the host — the fusion
    win evaporates and the watchdog's chunk budget starts timing host
    code.  Silent unless the lint caller declared fused intent."""
    k = getattr(ctx, 'fused_steps', None)
    if not k:
        return
    for _, eqn in ctx.walk():
        sev = _CHUNK_BREAKERS.get(eqn.primitive.name)
        if sev is None:
            continue
        f, l = _loc(eqn)
        yield Finding(
            'chunk-break', sev,
            f'{eqn.primitive.name} inside a step fused at '
            f'fused_steps={k}: each K-chunk would round-trip to the '
            f'host {k} times from inside one XLA dispatch, '
            'serializing the scan. Move the host work to chunk '
            'boundaries, express it in jnp, or run this step '
            'unfused (fused_steps=0).',
            file=f, line=l, origin='jaxpr')


# -- replicated-giant ---------------------------------------------------------

@register_rule('replicated-giant', HIGH)
def replicated_giant(ctx):
    """Giant constant-derived intermediates with a Mesh active.

    XLA's SPMD partitioner shards values whose lineage reaches a
    sharded input, but values derived ONLY from constants/literals
    (iota position grids, jnp.ones/tril masks, baked tables) are
    materialized replicated on every device unless explicitly
    constrained."""
    if ctx.mesh is None:
        return
    threshold = ctx.thresholds['replicated_bytes']
    n_dev = 1
    for v in dict(getattr(ctx.mesh, 'shape', {}) or {}).values():
        n_dev *= v

    # One dependency graph across ALL nesting levels.  Exact wiring of
    # sub-jaxpr invars/outvars differs per primitive (scan carries,
    # cond branches, pjit 1:1); the conservative superset — sub invars
    # depend on all eqn inputs, eqn outputs depend on all sub outputs
    # — is sound for both analyses below.
    deps = {}           # var -> set of vars it is computed from
    located = []        # (eqn, outvar) flag candidates
    const_roots = set(ctx.jaxpr.constvars)
    sync_invars = []    # inputs of every sharding_constraint anywhere
    for parent, eqn in ctx.walk():
        const_roots.update(parent.constvars)
        ins = {v for v in eqn.invars if not walker.is_literal(v)}
        subs = list(walker.subjaxprs(eqn))
        sub_outs = {v for s in subs for v in s.outvars
                    if not walker.is_literal(v)}
        for s in subs:
            for iv in s.invars:
                deps.setdefault(iv, set()).update(ins)
        for ov in eqn.outvars:
            deps.setdefault(ov, set()).update(ins | sub_outs)
        if eqn.primitive.name == 'sharding_constraint':
            sync_invars.extend(ins)
        else:
            located.extend((eqn, ov) for ov in eqn.outvars)

    # constant-derived: depends on nothing fed through the top invars
    top_in = set(ctx.jaxpr.invars)
    derived = set(const_roots)
    changed = True
    while changed:
        changed = False
        for v, ds in deps.items():
            if v not in derived and v not in top_in and \
                    all(d in derived for d in ds):
                derived.add(v)
                changed = True
    # transitively feeding a sharding_constraint: XLA propagates the
    # requested sharding backward through the producing fusion
    constrained = set()
    frontier = list(sync_invars)
    while frontier:
        v = frontier.pop()
        if v in constrained:
            continue
        constrained.add(v)
        frontier.extend(deps.get(v, ()))

    outset = set(ctx.jaxpr.outvars)
    for eqn, ov in located:
        nbytes = walker.aval_bytes(ov.aval)
        if (nbytes >= threshold and ov in derived
                and ov not in constrained and ov not in outset):
            f, l = _loc(eqn)
            yield Finding(
                'replicated-giant', HIGH,
                f'{_fmt_aval(ov.aval)} ({nbytes / (1 << 20):.0f} MiB) '
                'is derived only from constants and carries no '
                f'sharding constraint: with the active {n_dev}-device '
                'mesh it is replicated into EVERY device\'s HBM. Wrap '
                'it in jax.lax.with_sharding_constraint or derive it '
                'from a sharded input.',
                file=f, line=l, origin='jaxpr')


# -- amp-promotion ------------------------------------------------------------

_MATMUL_PRIMS = {'dot_general', 'conv_general_dilated'}


@register_rule('amp-promotion', WARN)
def amp_promotion(ctx):
    """f32 creep inside low-precision regions."""
    prod = ctx.producer_map()

    def upcast_of(v):
        """The convert_element_type eqn that made `v` f32 from a
        low-precision value, else None."""
        e = prod.get(v)
        if e is None or e.primitive.name != 'convert_element_type':
            return None
        src = e.invars[0]
        src_dtype = getattr(getattr(src, 'aval', None), 'dtype', None)
        dst_dtype = getattr(v.aval, 'dtype', None)
        if src_dtype in _LOW_PRECISION and dst_dtype == jnp.float32:
            return e
        return None

    seen_lines = set()
    for _, eqn in ctx.walk():
        if eqn.primitive.name in _MATMUL_PRIMS:
            operands = [v for v in eqn.invars if not walker.is_literal(v)]
            ups = [upcast_of(v) for v in operands]
            # flag only when EVERY operand was upcast from low
            # precision: that matmul could have run on the fast
            # bf16 MXU path with an f32 accumulator; a genuinely-f32
            # operand (softmax weights etc.) legitimately forces f32
            if operands and all(u is not None for u in ups):
                f, l = _loc(ups[0])
                if (f, l) in seen_lines:
                    continue
                seen_lines.add((f, l))
                yield Finding(
                    'amp-promotion', WARN,
                    f'{eqn.primitive.name} operands are upcast '
                    'bf16/f16 -> f32 before the contraction: the MXU '
                    'then runs the ~8x slower f32 path and HBM reads '
                    'double. Keep operands in the low dtype and pass '
                    'preferred_element_type=jnp.float32 for the f32 '
                    'accumulator.',
                    file=f, line=l, origin='jaxpr')
            continue
        # f32 literal dragging a low-precision value up to f32
        out_dtypes = [getattr(getattr(ov, 'aval', None), 'dtype', None)
                      for ov in eqn.outvars]
        if not any(d == jnp.float32 for d in out_dtypes):
            continue
        lit_f32 = any(
            walker.is_literal(v)
            and getattr(v.aval, 'dtype', None) == jnp.float32
            and not getattr(v.aval, 'weak_type', False)
            for v in eqn.invars)
        # the promoted operand is either still low precision or was
        # just upcast by the promotion's inserted convert_element_type
        has_low = any(
            not walker.is_literal(v)
            and (getattr(getattr(v, 'aval', None), 'dtype', None)
                 in _LOW_PRECISION or upcast_of(v) is not None)
            for v in eqn.invars)
        if lit_f32 and has_low:
            f, l = _loc(eqn)
            yield Finding(
                'amp-promotion', WARN,
                f'non-weak f32 constant in `{eqn.primitive.name}` '
                'promotes a bf16/f16 intermediate to f32 — the rest '
                'of the chain then runs f32. Use a Python literal '
                '(weak-typed) or cast the constant to the low dtype.',
                file=f, line=l, origin='jaxpr')


# -- donation-violation -------------------------------------------------------

@register_rule('donation-violation', HIGH)
def donation_violation(ctx):
    """Donated inputs XLA cannot alias to any output."""
    if not ctx.donate_argnums or not ctx.arg_leaf_ranges:
        return
    # multiset of output (shape, dtype) available for aliasing
    avail = {}
    for ov in ctx.jaxpr.outvars:
        aval = getattr(ov, 'aval', None)
        key = (tuple(getattr(aval, 'shape', ())),
               str(getattr(aval, 'dtype', '?')))
        avail[key] = avail.get(key, 0) + 1
    invars = ctx.jaxpr.invars
    for argpos in ctx.donate_argnums:
        if argpos >= len(ctx.arg_leaf_ranges):
            continue
        start, stop = ctx.arg_leaf_ranges[argpos]
        for i in range(start, stop):
            aval = invars[i].aval
            key = (tuple(aval.shape), str(aval.dtype))
            if avail.get(key, 0) > 0:
                avail[key] -= 1
                continue
            yield Finding(
                'donation-violation', HIGH,
                f'donated argument {argpos} leaf {_fmt_aval(aval)} has '
                'no same-shape/dtype output to alias: XLA frees the '
                'buffer, the caller\'s array is dead after the call '
                '(reading it raises), and the donation saved no '
                'memory. Return an updated value of the same '
                'shape/dtype or stop donating this argument.',
                origin='jaxpr')


# -- constant-capture ---------------------------------------------------------

@register_rule('constant-capture', WARN)
def constant_capture(ctx):
    """Large arrays closed over and baked into the jaxpr."""
    threshold = ctx.thresholds['const_bytes']
    high_at = ctx.thresholds['const_bytes_high']
    # first use of each constvar gives the best source location
    first_use = {}
    for _, eqn in ctx.walk():
        for v in eqn.invars:
            if not walker.is_literal(v) and v not in first_use:
                first_use[v] = eqn
    for cvar, cval in zip(ctx.jaxpr.constvars, ctx.consts):
        nbytes = getattr(cval, 'nbytes', None)
        if nbytes is None:
            try:
                nbytes = np.asarray(cval).nbytes
            except Exception:
                continue
        if nbytes < threshold:
            continue
        f, l = (None, None)
        if cvar in first_use:
            f, l = _loc(first_use[cvar])
        sev = HIGH if nbytes >= high_at else WARN
        yield Finding(
            'constant-capture', sev,
            f'{_fmt_aval(cvar.aval)} ({nbytes / (1 << 20):.1f} MiB) is '
            'captured as a jaxpr CONSTANT: it is baked into the '
            'compiled module (a new value means a full recompile, and '
            'the artifact carries the bytes). Pass it as an explicit '
            'argument instead of closing over it.',
            file=f, line=l, origin='jaxpr')
