"""Lowered-HLO SPMD audit — what the partitioner DID, not what the
trace asked for.

The jaxpr rules (``analysis.rules``) see the program before XLA's
GSPMD partitioner runs, so three first-order TPU costs are invisible
to them: where reshards/collectives actually land, how many bytes they
move, and which intermediates end up materialized at FULL size on
every device.  This module lowers a step through ``jax.jit(...)
.lower().compile()`` — abstract shapes only, no device execution, and
it works under ``JAX_PLATFORMS=cpu`` with a forced
``--xla_force_host_platform_device_count`` mesh — then parses the
compiled (post-partitioner, per-device, scheduled) HLO text into a
lightweight op graph and runs a second rule registry over it:

``replicated-giant-hlo``  per-device buffers at the FULL global shape
                          of a traced intermediate: the partitioner
                          left them replicated (catches input-derived
                          values the jaxpr const-dataflow rule cannot)
``collective-cost``       census of all-reduce / all-gather /
                          reduce-scatter / all-to-all /
                          collective-permute with per-op byte counts
                          and a ring latency+bandwidth estimate
                          (``analysis.costmodel``); flags oversized
                          collectives and all-gathers feeding only
                          elementwise consumers (could run sharded)
``resharding``            all-to-all ops the partitioner inserted
                          because adjacent shardings conflict
``peak-memory``           liveness walk over the scheduled entry
                          computation: per-device high-water estimate
                          against a configurable HBM budget

Entry points: ``audit`` (lower a callable), ``audit_text`` (a compiled
HLO module already in hand — ParallelTrainer reuses its census text).
Reports are ordinary ``analysis.LintReport``s (findings carry
``origin='hlo'`` and the source location from HLO metadata, so
``# tpu-lint: disable=`` suppressions apply) with an ``extras`` dict
(collective census, predicted cost, peak memory) that
``tools/tpu_lint.py --hlo`` and the ``collective_cost`` telemetry
event surface.
"""
import math
import re

from . import costmodel
from .findings import Finding, LintReport, HIGH, WARN, INFO
from .rules import DEFAULT_THRESHOLDS as _JAXPR_THRESHOLDS

__all__ = ['parse_module', 'HloModule', 'HloComputation', 'HloInstr',
           'buffer_bytes', 'collective_census', 'collective_instrs',
           'peak_memory',
           'HLO_RULES', 'register_hlo_rule', 'HloRuleContext',
           'run_hlo_rules', 'DEFAULT_HLO_THRESHOLDS', 'audit',
           'audit_text', 'auto_shardings', 'lower_text']

DEFAULT_HLO_THRESHOLDS = {
    # replicated-giant-hlo: per-device bytes of an intermediate still
    # at its full traced shape after partitioning (same bar as the
    # jaxpr rule: the two are one diagnosis at two compile stages)
    'replicated_bytes': _JAXPR_THRESHOLDS['replicated_bytes'],
    # collective-cost: wire bytes of ONE collective worth flagging
    'collective_wire_warn': 64 << 20,
    'collective_wire_high': 1 << 30,
    # peak-memory: per-device HBM budget (v5e-class default; real runs
    # pass the chip's budget via thresholds / tpu_lint --hbm-gb)
    'hbm_bytes': 16 << 30,
    'hbm_warn_frac': 0.8,
    # cost-model knobs (costmodel defaults; exposed for A/B vs chips)
    'link_bw_gbps': costmodel.DEFAULT_LINK_BW_GBPS,
    'link_latency_us': costmodel.DEFAULT_LINK_LATENCY_US,
    # optional costmodel.Calibration (measured alpha/beta per op kind,
    # from tools/calibrate_costmodel.py) — overrides the analytic
    # estimate in the census and everything built on it (the planner)
    'calibration': None,
}

_DTYPE_BYTES = {
    'f64': 8, 'f32': 4, 'f16': 2, 'bf16': 2, 'f8e4m3fn': 1,
    'f8e5m2': 1, 's64': 8, 's32': 4, 's16': 2, 's8': 1, 'u64': 8,
    'u32': 4, 'u16': 2, 'u8': 1, 'pred': 1, 'c64': 8, 'c128': 16,
}

# `%name = f32[8,128]{1,0} opcode(...)` / tuple-typed
# `%name = (f32[2]{0}, s32[]{:T(128)}) opcode(...)`; TPU tuple layouts
# nest parens, hence the inner group (same shape as profiler's parser)
_INSTR_RE = re.compile(
    r'^\s*(ROOT\s+)?%?([\w.\-]+)\s*=\s*'
    r'(\((?:[^()]|\([^()]*\))*\)|\S+)\s+([\w\-]+)\(')
_BUF_RE = re.compile(r'(\w+)\[([\d,]*)\]')
# computation header: `ENTRY %main (...) -> ... {` / `%body.12 (...) {`
_COMP_RE = re.compile(r'^(ENTRY\s+)?%?([\w.\-]+)\s*\([^)]*\)[^{]*{')
_META_RE = re.compile(r'source_file="([^"]*)"\s+source_line=(\d+)')
# iota replica groups: `replica_groups=[8,2]<=[16]` (groups x size)
_GROUPS_IOTA_RE = re.compile(r'replica_groups=\[(\d+),(\d+)\]<=')
_GROUPS_LIST_RE = re.compile(r'replica_groups=\{\{([\d,]*)\}')
_CALLED_RE = re.compile(
    r'(?:calls|to_apply|body|condition|true_computation|'
    r'false_computation|branch_computations)='
    r'(\{[^}]*\}|%[\w.\-]+)')
_NUM_PARTITIONS_RE = re.compile(r'num_partitions=(\d+)')
_OPERAND_NAME_RE = re.compile(r'%([\w.\-]+)')

# ops whose "output" aliases/repackages an existing buffer — no new
# HBM allocation worth accounting
_ALIAS_OPS = frozenset((
    'parameter', 'tuple', 'get-tuple-element', 'bitcast'))

# elementwise consumers an all-gather could have run sharded through
# (kLoop fusions count: their bodies are elementwise by construction)
_ELEMENTWISE_OPS = frozenset((
    'add', 'subtract', 'multiply', 'divide', 'maximum', 'minimum',
    'power', 'exponential', 'exponential-minus-one', 'log', 'log-plus-one',
    'tanh', 'logistic', 'negate', 'abs', 'sign', 'rsqrt', 'sqrt',
    'compare', 'select', 'and', 'or', 'not', 'xor', 'clamp', 'convert',
    'copy'))


def buffer_bytes(type_spec):
    """Total bytes of one HLO type spec (sums tuple components)."""
    total = 0
    for dtype, shape in _BUF_RE.findall(type_spec):
        n = math.prod(int(d) for d in shape.split(',') if d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def _first_shape(type_spec):
    """Dims tuple of the first (or only) buffer in a type spec."""
    m = _BUF_RE.search(type_spec)
    if not m:
        return None
    return tuple(int(d) for d in m.group(2).split(',') if d)


def _balanced(text, open_idx, open_ch='(', close_ch=')'):
    """Contents of the balanced group starting at text[open_idx]."""
    depth = 0
    for i in range(open_idx, len(text)):
        c = text[i]
        if c == open_ch:
            depth += 1
        elif c == close_ch:
            depth -= 1
            if depth == 0:
                return text[open_idx + 1:i], i
    return text[open_idx + 1:], len(text)


class HloInstr:
    """One instruction of the compiled module."""

    __slots__ = ('name', 'opcode', 'type_spec', 'bytes', 'operands',
                 'sharding', 'group_size', 'called', 'fusion_kind',
                 'call_target', 'file', 'line', 'is_root')

    def __init__(self, name, opcode, type_spec, operands=(), sharding=None,
                 group_size=None, called=(), fusion_kind=None, file=None,
                 line=None, is_root=False, call_target=None):
        self.name = name
        self.opcode = opcode
        self.type_spec = type_spec
        self.bytes = buffer_bytes(type_spec)
        self.operands = tuple(operands)
        self.sharding = sharding
        self.group_size = group_size    # replica group size (collectives)
        self.called = tuple(called)     # names of called computations
        self.fusion_kind = fusion_kind  # kLoop/kOutput/... for fusions
        self.call_target = call_target  # custom-call target name
        self.file = file
        self.line = line
        self.is_root = is_root

    @property
    def shape(self):
        return _first_shape(self.type_spec)

    def __repr__(self):
        return (f'HloInstr({self.name} = {self.type_spec} '
                f'{self.opcode}(...))')


class HloComputation:
    __slots__ = ('name', 'is_entry', 'instrs', 'index')

    def __init__(self, name, is_entry=False):
        self.name = name
        self.is_entry = is_entry
        self.instrs = []
        self.index = {}     # instr name -> HloInstr

    @property
    def is_fusion(self):
        return 'fused' in self.name

    def add(self, instr):
        self.instrs.append(instr)
        self.index[instr.name] = instr


class HloModule:
    """Light op graph of one compiled (per-device) HLO module."""

    __slots__ = ('computations', 'entry', 'num_partitions',
                 'is_scheduled')

    def __init__(self):
        self.computations = {}
        self.entry = None
        self.num_partitions = 1
        self.is_scheduled = False

    def work_computations(self):
        """Entry + called non-fusion computations (while/cond bodies,
        reduce regions): the instructions that are scheduled work.
        Fusion bodies stay register-resident — their HBM traffic is
        the single ``fusion`` call site."""
        for comp in self.computations.values():
            if comp.is_entry or not comp.is_fusion:
                yield comp

    def walk(self):
        """(computation, instr) over every work computation."""
        for comp in self.work_computations():
            for ins in comp.instrs:
                yield comp, ins


def _parse_sharding(line):
    i = line.find('sharding={')
    if i < 0:
        return None
    body, _ = _balanced(line, i + len('sharding='), '{', '}')
    return '{' + body + '}'


def _parse_instr(line, num_partitions):
    m = _INSTR_RE.match(line)
    if not m:
        return None
    root, name, type_spec, opcode = m.groups()
    operand_body, end = _balanced(line, m.end() - 1)
    operands = _OPERAND_NAME_RE.findall(operand_body)
    rest = line[end + 1:]
    group_size = None
    if opcode.split('-start')[0] in costmodel.COLLECTIVE_OPS or \
            opcode.startswith(('all-', 'reduce-scatter', 'collective-')):
        gm = _GROUPS_IOTA_RE.search(rest)
        if gm:
            group_size = int(gm.group(2))
        else:
            gm = _GROUPS_LIST_RE.search(rest)
            if gm:
                group_size = len([d for d in gm.group(1).split(',') if d])
            else:
                group_size = num_partitions
    called = []
    for cm in _CALLED_RE.finditer(rest):
        called.extend(_OPERAND_NAME_RE.findall(cm.group(1)))
    fusion_kind = None
    if opcode == 'fusion':
        km = re.search(r'kind=(\w+)', rest)
        fusion_kind = km.group(1) if km else None
    call_target = None
    if opcode == 'custom-call':
        tm = re.search(r'custom_call_target="([^"]*)"', rest)
        call_target = tm.group(1) if tm else None
    file = line_no = None
    mm = _META_RE.search(rest)
    if mm:
        file, line_no = mm.group(1), int(mm.group(2))
    return HloInstr(name, opcode, type_spec, operands=operands,
                    sharding=_parse_sharding(rest), group_size=group_size,
                    called=called, fusion_kind=fusion_kind, file=file,
                    line=line_no, is_root=bool(root),
                    call_target=call_target)


def parse_module(text):
    """Compiled HLO text -> HloModule (computations, instrs, graph)."""
    mod = HloModule()
    current = None
    for line in text.splitlines():
        if line.startswith('HloModule'):
            pm = _NUM_PARTITIONS_RE.search(line)
            if pm:
                mod.num_partitions = int(pm.group(1))
            mod.is_scheduled = 'is_scheduled=true' in line
            continue
        cm = _COMP_RE.match(line)
        if cm:
            current = HloComputation(cm.group(2),
                                     is_entry=bool(cm.group(1)))
            mod.computations[current.name] = current
            if current.is_entry:
                mod.entry = current
            continue
        if line.startswith('}'):
            current = None
            continue
        if current is None:
            continue
        ins = _parse_instr(line, mod.num_partitions)
        if ins is not None:
            current.add(ins)
    return mod


# -- collective census + cost -------------------------------------------------

def _collective_base(opcode):
    for suffix in ('-start', '-done'):
        if opcode.endswith(suffix):
            opcode = opcode[:-len(suffix)]
    return opcode if opcode in costmodel.COLLECTIVE_OPS else None


def _collective_bytes(comp, ins, base):
    """Per-device buffer size the ring moves: the operand buffers
    summed (collectives are variadic — a grad-bucketed all-reduce or
    tuple all-to-all moves every piece; the '-start' tuple OUTPUT type
    would double-count, so operand defs are the source of truth)."""
    total = 0
    for op in ins.operands:
        src = comp.index.get(op)
        if src is not None:
            total += src.bytes
    return total or ins.bytes


def _collective_wire_dtype(comp, ins):
    """The dtype actually on the wire for one collective: the element
    type of its byte-dominant operand (quantized collectives move s8
    payloads next to tiny f32 scale buffers — the payload dtype is
    the honest tag).  Falls back to the output type spec."""
    best, best_b = None, -1
    for op in ins.operands:
        src = comp.index.get(op)
        if src is None:
            continue
        m = _BUF_RE.search(src.type_spec)
        if m and src.bytes > best_b:
            best, best_b = m.group(1), src.bytes
    if best is None:
        m = _BUF_RE.search(ins.type_spec)
        best = m.group(1) if m else None
    return best


def _short(type_spec, limit=48):
    return type_spec if len(type_spec) <= limit \
        else type_spec[:limit - 3] + '...'


def collective_census(module, *, bw_gbps=None, latency_us=None,
                      mesh_shape=None, calibration=None):
    """Per-collective census with predicted cost.

    Returns {base_opcode: {calls, bytes, wire_bytes, est_us, phases,
    max_wire_bytes, group_size, axes, file, line}} — ``bytes`` is
    per-device buffer bytes summed over call sites (comparable to the
    telemetry census), ``wire_bytes``/``est_us``/``phases`` the
    cost-model prediction.  With ``mesh_shape`` in hand each replica
    group is decomposed onto its torus axes
    (``costmodel.axes_for_group``) — a dp×tp mesh is no longer costed
    as one flat ring over all chips — and a ``calibration`` table
    substitutes measured alpha/beta.  '-done' halves of async pairs
    are not double counted.
    """
    # ONE walk/cost implementation: the per-instruction index is the
    # source of truth (the trace join reads it directly), and the
    # census is its aggregation by base opcode
    rows = {}
    for r in collective_instrs(module, bw_gbps=bw_gbps,
                               latency_us=latency_us,
                               mesh_shape=mesh_shape,
                               calibration=calibration).values():
        row = rows.setdefault(r['op'], {
            'calls': 0, 'bytes': 0, 'wire_bytes': 0, 'est_us': 0.0,
            'phases': 0, 'max_wire_bytes': 0, 'max_est_us': 0.0,
            'group_size': r['group_size'], 'axes': r['axes'],
            'wire_dtype': r.get('wire_dtype'),
            'file': None, 'line': None})
        row['calls'] += 1
        row['bytes'] += r['bytes']
        row['wire_bytes'] += r['wire_bytes']
        row['est_us'] = round(row['est_us'] + r['est_us'], 3)
        row['phases'] += r['phases']
        if r['wire_bytes'] > row['max_wire_bytes']:
            # group_size/est ride along: on a multi-axis mesh one base
            # opcode mixes group sizes (tp=2 activation vs dp=4 grad
            # all-reduces) and the flag must describe the worst call
            row['max_wire_bytes'] = r['wire_bytes']
            row['max_est_us'] = r['est_us']
            row['group_size'] = r['group_size']
            row['axes'] = r['axes']
            row['wire_dtype'] = r.get('wire_dtype')
            row['file'], row['line'] = r['file'], r['line']
    return rows


def collective_instrs(module, *, bw_gbps=None, latency_us=None,
                      mesh_shape=None, calibration=None):
    """Per-INSTRUCTION collective index of a compiled module — the
    join key for profiled-trace matching (``profiler.trace.
    match_collectives``): a captured trace times ops by instruction
    name, and this index carries each collective instruction's base
    opcode + byte/replica-group signature plus the cost-model
    prediction for exactly that call.

    Returns {instr_name: {op, bytes, wire_bytes, phases, est_us,
    group_size, axes, file, line}} — ``bytes`` is the counted buffer
    (gathered size for all-gather, operand size otherwise), the same
    convention as :func:`collective_census`, whose rows are these
    aggregated by base opcode.  '-done' halves of async pairs are
    skipped (the '-start' op owns the transfer).

    HLO names are unique per COMPUTATION, not per module: when a
    while/scan body reuses an entry-computation name, the later
    instruction keys as ``name@computation`` so no row is lost — the
    trace join strips the ``@…`` qualifier before lookup (a trace
    merges same-named events anyway).
    """
    bw, lat = costmodel.effective_links(bw_gbps, latency_us,
                                        calibration)
    out = {}
    for comp, ins in module.walk():
        if ins.opcode.endswith('-done'):
            continue
        base = _collective_base(ins.opcode)
        if base is None:
            continue
        n = ins.group_size or module.num_partitions
        axes = costmodel.axes_for_group(mesh_shape, n)
        local = _collective_bytes(comp, ins, base)
        counted = local * n if base == 'all-gather' else local
        cost = costmodel.torus_cost(base, counted, axes, bw_gbps=bw,
                                    latency_us=lat,
                                    calibration=calibration)
        key = ins.name if ins.name not in out \
            else f'{ins.name}@{comp.name}'
        out[key] = {
            'op': base, 'bytes': counted,
            'wire_bytes': cost['wire_bytes'],
            'phases': cost['phases'], 'est_us': cost['est_us'],
            'group_size': n, 'axes': cost['axes'],
            'wire_dtype': _collective_wire_dtype(comp, ins),
            'file': ins.file, 'line': ins.line}
    return out


# -- peak-memory liveness -----------------------------------------------------

def _comp_peak(module, comp, memo):
    """(peak_bytes, param_bytes) of one computation, walking the
    schedule: a buffer is born at its defining instruction and dies
    after its last use; called non-fusion computations contribute
    their transient peak at the call site; fusion internals are
    register-resident."""
    if comp.name in memo:
        return memo[comp.name]
    memo[comp.name] = (0, 0)    # cycle guard (self-recursive comps)
    params = sum(i.bytes for i in comp.instrs
                 if i.opcode == 'parameter')
    last_use = {}
    for idx, ins in enumerate(comp.instrs):
        for op in ins.operands:
            last_use[op] = idx
    live = params
    peak = live
    for idx, ins in enumerate(comp.instrs):
        if ins.opcode != 'parameter':
            b = 0 if ins.opcode in _ALIAS_OPS else ins.bytes
            inner = 0
            if ins.opcode != 'fusion':
                for cname in ins.called:
                    sub = module.computations.get(cname)
                    if sub is None or sub.is_fusion:
                        continue
                    sp, spar = _comp_peak(module, sub, memo)
                    # the callee's params alias our operands (already
                    # live here) — only its transient excess stacks
                    inner = max(inner, sp - spar)
            live += b
            peak = max(peak, live + inner)
        for op in set(ins.operands):
            if last_use.get(op) == idx:
                src = comp.index.get(op)
                if src is not None and src.opcode != 'parameter' \
                        and src.opcode not in _ALIAS_OPS:
                    live -= src.bytes
    memo[comp.name] = (peak, params)
    return memo[comp.name]


def peak_memory(module):
    """Per-device high-water HBM estimate (bytes) of the scheduled
    entry computation.  Conservative: donation aliasing is not
    credited, so donated-in-place steps really peak a little lower."""
    if module.entry is None:
        return 0
    peak, _ = _comp_peak(module, module.entry, {})
    return peak


def peak_memory_report(module, top=8):
    """The liveness walk of :func:`peak_memory` over the ENTRY
    computation, instrumented: re-runs the same born-at-def /
    dies-after-last-use schedule tracking the live buffer set, and
    snapshots the largest contributors at the peak instant — so the
    memory observatory can say not just HOW HIGH the predicted
    high-water is but WHICH buffers stack it (with source attribution
    when the HLO carries metadata).

    Returns ``{'peak_bytes', 'param_bytes', 'at_instr',
    'contributors': [{name, opcode, bytes, file, line}, ...]}`` —
    contributors sorted largest-first, capped at `top`, parameters
    folded into one synthetic row.  peak_bytes matches
    :func:`peak_memory` minus callee-transient stacking (entry-local
    buffers only), so it is a floor of the full estimate, never above
    it."""
    empty = {'peak_bytes': 0, 'param_bytes': 0, 'at_instr': None,
             'contributors': []}
    if module.entry is None:
        return empty
    comp = module.entry
    params = sum(i.bytes for i in comp.instrs if i.opcode == 'parameter')
    last_use = {}
    for idx, ins in enumerate(comp.instrs):
        for op in ins.operands:
            last_use[op] = idx
    live_set = {}               # instr name -> bytes (non-param buffers)
    live = params
    peak = live
    at_instr = None
    peak_set = {}
    for idx, ins in enumerate(comp.instrs):
        if ins.opcode != 'parameter':
            if ins.opcode not in _ALIAS_OPS and ins.bytes:
                live_set[ins.name] = ins.bytes
                live += ins.bytes
            if live > peak:
                peak = live
                at_instr = ins.name
                peak_set = dict(live_set)
        for op in set(ins.operands):
            if last_use.get(op) == idx:
                src = comp.index.get(op)
                if src is not None and src.opcode != 'parameter' \
                        and src.opcode not in _ALIAS_OPS:
                    live -= src.bytes
                    live_set.pop(op, None)
    contributors = []
    if params:
        contributors.append({'name': '(parameters)',
                             'opcode': 'parameter', 'bytes': params,
                             'file': None, 'line': None})
    for name, b in sorted(peak_set.items(), key=lambda kv: -kv[1]):
        ins = comp.index.get(name)
        contributors.append({
            'name': name,
            'opcode': ins.opcode if ins is not None else '?',
            'bytes': b,
            'file': ins.file if ins is not None else None,
            'line': ins.line if ins is not None else None})
    contributors.sort(key=lambda c: -c['bytes'])
    return {'peak_bytes': peak, 'param_bytes': params,
            'at_instr': at_instr, 'contributors': contributors[:top]}


# -- rule registry ------------------------------------------------------------

HLO_RULES = {}


def register_hlo_rule(rule_id, severity):
    """Register ``fn(ctx) -> iterable[Finding]`` under `rule_id` (the
    id suppression comments / disable= lists name).  `severity` is the
    WORST level the rule can emit (documentation for tooling that
    lists the registry; each Finding carries its own severity).
    Mirrors rules.register_rule but runs over the compiled-HLO op
    graph."""
    def deco(fn):
        HLO_RULES[rule_id] = (severity, fn)
        fn.rule_id = rule_id
        return fn
    return deco


class HloRuleContext:
    """Everything an HLO rule may inspect for one audit."""

    def __init__(self, module, *, mesh_shape=None, thresholds=None,
                 global_shapes=None, name=None):
        self.module = module
        self.mesh_shape = dict(mesh_shape or {})
        self.thresholds = dict(DEFAULT_HLO_THRESHOLDS)
        self.thresholds.update(thresholds or {})
        # shape tuples of big TRACED intermediates (global, pre-
        # partitioner) — the replicated-giant join key; None when the
        # caller could not re-trace the step
        self.global_shapes = global_shapes
        self.name = name
        self.summary = {'n_partitions': module.num_partitions,
                        'mesh': self.mesh_shape or None}
        self._census = None

    def census(self):
        if self._census is None:
            self._census = collective_census(
                self.module,
                bw_gbps=self.thresholds['link_bw_gbps'],
                latency_us=self.thresholds['link_latency_us'],
                mesh_shape=self.mesh_shape or None,
                calibration=self.thresholds.get('calibration'))
            self.summary['collectives'] = self._census
            self.summary['collective_wire_bytes'] = sum(
                r['wire_bytes'] for r in self._census.values())
            self.summary['collective_est_us'] = round(sum(
                r['est_us'] for r in self._census.values()), 3)
        return self._census


def run_hlo_rules(ctx, disable=()):
    out = []
    for rule_id, (_, fn) in HLO_RULES.items():
        if rule_id in disable:
            continue
        out.extend(fn(ctx))
    return out


def _mib(b):
    return b / (1 << 20)


def _maybe_local_shard(shape, global_shapes, mesh_shape, n_partitions):
    """True when `shape` could equally be the per-device SHARD of a
    larger traced global: scaling its dimensions by mesh-axis factors
    (one axis per dim, or several axes across several dims — GSPMD
    shards 2D too) lands on another global shape.  Such a buffer is
    ambiguous — the bare dims tuple cannot distinguish 'replicated at
    full traced shape' from 'correctly partitioned slice of a bigger
    intermediate that happens to collide'."""
    factors = {1}
    for s in (v for v in mesh_shape.values() if v > 1):
        factors |= {f * s for f in factors}
    factors.add(max(n_partitions, 1))
    factors.discard(1)
    if not factors or len(shape) > 8:
        return False
    per_dim = (1,) + tuple(sorted(factors))
    total = max(factors)    # can't shard more ways than devices exist

    def expand(cur, d, scale):
        if d == len(cur):
            return scale > 1 and cur in global_shapes
        for k in per_dim:
            if scale * k > total:
                continue
            nxt = cur if k == 1 else \
                cur[:d] + (cur[d] * k,) + cur[d + 1:]
            if expand(nxt, d + 1, scale * k):
                return True
        return False

    return expand(shape, 0, 1)


@register_hlo_rule('replicated-giant-hlo', HIGH)
def replicated_giant_hlo(ctx):
    """Per-device buffers still at a FULL traced (global) shape.

    The jaxpr rule can only prove replication for constant-derived
    values; after the partitioner every buffer in the per-device
    module IS a per-device buffer, so an intermediate whose local
    shape still equals the global shape of a traced intermediate was
    left replicated — input-derived or not."""
    if ctx.module.num_partitions <= 1:
        return
    threshold = ctx.thresholds['replicated_bytes']
    for comp, ins in ctx.module.walk():
        if (ins.opcode in _ALIAS_OPS or ins.is_root
                or ins.bytes < threshold):
            continue
        shape = ins.shape
        if shape is None:
            continue
        if ctx.global_shapes is not None and shape not in ctx.global_shapes:
            continue    # partitioned: its global shape was bigger
        verified = ctx.global_shapes is not None and not _maybe_local_shard(
            shape, ctx.global_shapes, ctx.mesh_shape,
            ctx.module.num_partitions)
        yield Finding(
            'replicated-giant-hlo', HIGH if verified else WARN,
            f'{ins.opcode} buffer {_short(ins.type_spec)} '
            f'({_mib(ins.bytes):.0f} MiB) '
            + ('still has its full traced shape after the SPMD '
               'partitioner: it is materialized replicated in EVERY '
               f'device\'s HBM ({ctx.module.num_partitions} devices). '
               'Derive it from sharded operands or wrap it in '
               'jax.lax.with_sharding_constraint.'
               if verified else
               'is large per device after partitioning; check its '
               'sharding (replication unverified: '
               + ('it also matches a shard of a larger traced '
                  'intermediate).'
                  if ctx.global_shapes is not None else
                  'trace unavailable).')),
            file=ins.file, line=ins.line, origin='hlo')


@register_hlo_rule('collective-cost', HIGH)
def collective_cost(ctx):
    """Oversized or avoidably-placed collectives (EQuARX-style)."""
    census = ctx.census()
    warn_at = ctx.thresholds['collective_wire_warn']
    high_at = ctx.thresholds['collective_wire_high']
    for base, row in census.items():
        worst = row['max_wire_bytes']
        if worst < warn_at:
            continue
        yield Finding(
            'collective-cost', HIGH if worst >= high_at else WARN,
            f'{base} over {row["group_size"]} devices puts '
            f'{_mib(worst):.0f} MiB on the ICI wire in one call '
            f'(~{row["max_est_us"]:.0f} us ring estimate): consider '
            'sharding the value, reduce-scatter + sharded consumer '
            'instead of all-reduce, or overlapping via async '
            'collectives.',
            file=row['file'], line=row['line'], origin='hlo')
    # all-gather whose every consumer is elementwise: the gather could
    # move AFTER the elementwise work (or vanish) by keeping it sharded
    seen_lines = set()
    for comp, ins in ctx.module.walk():
        if _collective_base(ins.opcode) != 'all-gather' \
                or ins.opcode.endswith('-done'):
            continue
        if (ins.file, ins.line) in seen_lines:
            continue
        out_names = {ins.name}
        # async pair: consumers read the -done instr's output
        for other in comp.instrs:
            if other.opcode.endswith('-done') and \
                    ins.name in other.operands:
                out_names.add(other.name)
        consumers = [o for o in comp.instrs
                     if o is not ins and not o.opcode.endswith('-done')
                     and out_names.intersection(o.operands)]
        if not consumers:
            continue
        if all(c.opcode in _ELEMENTWISE_OPS
               or (c.opcode == 'fusion' and c.fusion_kind == 'kLoop')
               for c in consumers):
            seen_lines.add((ins.file, ins.line))
            yield Finding(
                'collective-cost', WARN,
                f'all-gather of {_short(ins.type_spec)} feeds only '
                'elementwise '
                'consumers: the elementwise work could run on the '
                'sharded value and the gather move after it (or into '
                'the consumer that actually needs it).',
                file=ins.file, line=ins.line, origin='hlo')


@register_hlo_rule('resharding', WARN)
def resharding(ctx):
    """all-to-all = the partitioner resharding between adjacent ops
    whose requested shardings conflict (e.g. P('dp', None) feeding an
    op constrained to P(None, 'dp')).

    Always WARN, never HIGH: a user-requested collective
    (distributed.alltoall in an expert-parallel layer) lowers to the
    SAME opcode and the HLO text cannot tell the two apart — a
    deliberate MoE dispatch must not fail the zero-high gates."""
    for comp, ins in ctx.module.walk():
        if _collective_base(ins.opcode) != 'all-to-all' \
                or ins.opcode.endswith('-done'):
            continue
        local = _collective_bytes(comp, ins, 'all-to-all')
        yield Finding(
            'resharding', WARN,
            f'all-to-all ({_short(ins.type_spec)}, '
            f'{_mib(local):.1f} MiB per device): if not a deliberate '
            'collective (expert dispatch), the partitioner inserted '
            'it because adjacent ops request conflicting shardings — '
            'align the shardings (or constrain once, early) to delete '
            'the transpose traffic.',
            file=ins.file, line=ins.line, origin='hlo')


@register_hlo_rule('peak-memory', HIGH)
def peak_memory_rule(ctx):
    """Liveness high-water vs the HBM budget."""
    peak = peak_memory(ctx.module)
    ctx.summary['peak_bytes'] = peak
    # liveness fidelity: the walk follows instruction order, which is
    # the real schedule only when the backend emitted one
    ctx.summary['peak_schedule'] = (
        'scheduled' if ctx.module.is_scheduled else 'def-order')
    budget = ctx.thresholds['hbm_bytes']
    ctx.summary['hbm_budget_bytes'] = budget
    frac = ctx.thresholds['hbm_warn_frac']
    if peak >= budget:
        sev = HIGH
    elif peak >= frac * budget:
        sev = WARN
    else:
        return
    yield Finding(
        'peak-memory', sev,
        f'estimated per-device peak {peak / (1 << 30):.2f} GiB vs '
        f'{budget / (1 << 30):.2f} GiB HBM budget'
        + (f' ({peak / budget:.0%})' if budget else '')
        + ': the step will '
        + ('OOM' if sev == HIGH else 'run out of headroom')
        + ' on the real chip. Shard the largest live buffers, enable '
          'remat (strategy.recompute), or lower the batch.',
        origin='hlo')


# -- entry points -------------------------------------------------------------

def auto_shardings(mesh, example_args):
    """Forced-mesh heuristic for a bare callable: shard dim 0 of every
    array leaf over the mesh's first >1 axis when divisible, replicate
    the rest.  The compile-choke-point integrations pass their REAL
    shardings instead; this is for ``tpu_lint --hlo --jaxpr`` style
    audits where only shapes are known."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    axis = next((a for a in mesh.axis_names if mesh.shape[a] > 1), None)
    if axis is None:
        return None

    def leaf_sharding(leaf):
        shape = getattr(leaf, 'shape', None)
        if shape and len(shape) >= 1 and shape[0] % mesh.shape[axis] == 0:
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())

    return tuple(jax.tree_util.tree_map(leaf_sharding, a)
                 for a in example_args)


def global_big_shapes_of(closed, threshold):
    """Shape tuples of intermediates >= threshold bytes in an already-
    traced closed jaxpr — the pre-partitioner (global) side of the
    replicated-giant join.  Top-level outputs are excluded (returning
    params is legitimate).  analysis.lint stashes this on its report
    so the HLO escalation at the choke points can skip re-tracing."""
    from . import walker as _w
    shapes = set()
    outset = set(closed.jaxpr.outvars)
    for _, eqn in _w.walk(closed.jaxpr):
        for ov in eqn.outvars:
            if ov in outset:
                continue
            if _w.aval_bytes(ov.aval) >= threshold:
                shapes.add(tuple(int(d) for d in ov.aval.shape))
    return shapes


def _global_big_shapes(fn, example_args, example_kwargs, threshold):
    """Trace `fn` and collect its big global shapes; None when the
    trace fails (the audit then degrades to WARN)."""
    try:
        from . import walker
        closed = walker.trace_jaxpr(fn, *example_args, **example_kwargs)
    except Exception:
        return None
    return global_big_shapes_of(closed, threshold)


def audit_text(text, *, mesh=None, thresholds=None, disable=(),
               global_shapes=None, name=None):
    """Run the HLO rules over compiled HLO text already in hand
    (ParallelTrainer's census path).  Returns a LintReport whose
    ``extras`` carry the census / peak-memory summary."""
    from .ast_lint import apply_suppressions
    module = parse_module(text)
    mesh_shape = dict(getattr(mesh, 'shape', mesh or {}) or {})
    ctx = HloRuleContext(module, mesh_shape=mesh_shape,
                         thresholds=thresholds,
                         global_shapes=global_shapes, name=name)
    findings = run_hlo_rules(ctx, disable=disable)
    ctx.census()                      # always fill the summary
    ctx.summary.setdefault('peak_bytes', peak_memory(module))
    findings = [f for f in apply_suppressions(findings)
                if f.rule not in disable]
    report = LintReport(findings, name=name)
    report.extras = ctx.summary
    return report


def lower_text(fn, *example_args, jit_kwargs=None, lower_cache=None,
               cache_key=None, **example_kwargs):
    """``jax.jit(fn, **jit_kwargs).lower(...).compile().as_text()``
    with an optional cross-caller memo: when `lower_cache` (a plain
    dict) holds `cache_key`, the trace+lower+compile is skipped
    entirely.  This is how ``tpu_lint --plan`` and ``--hlo`` share
    ONE lowering per (target, mesh) pair instead of paying the
    partitioner twice for the same program.

    Keyed lowerings are additionally backed by the PERSISTENT compile
    cache's text tier (core.compile_cache): a repeated ``tpu_lint``
    invocation on unchanged targets reads its candidate modules off
    disk instead of compiling them again — dozens of planner
    candidates come back in seconds.  `cache_key` must be a
    deterministic, process-independent value (analysis.targets builds
    them from resolved specs and shapes); the persistent fingerprint
    folds in the jax version, backend, device count and package
    sources, so code or environment drift invalidates cleanly."""
    import jax
    if lower_cache is not None and cache_key is not None \
            and cache_key in lower_cache:
        return lower_cache[cache_key]
    fp = None
    if cache_key is not None:
        from ..core import compile_cache as _cc
        if _cc.enabled():
            fp = _cc.fingerprint('lower-text', key=cache_key)
            if fp is not None:
                text = _cc.get_text(fp, name='lower_text')
                if text is not None:
                    if lower_cache is not None:
                        lower_cache[cache_key] = text
                    return text
    text = jax.jit(fn, **(jit_kwargs or {})).lower(
        *example_args, **example_kwargs).compile().as_text()
    if fp is not None:
        from ..core import compile_cache as _cc
        _cc.put_text(fp, text, name='lower_text')
    if lower_cache is not None and cache_key is not None:
        lower_cache[cache_key] = text
    return text


def audit(fn, *example_args, mesh=None, in_shardings='auto',
          out_shardings=None, donate_argnums=(), jit_kwargs=None,
          thresholds=None, disable=(), name=None, global_shapes=None,
          lower_cache=None, cache_key=None, **example_kwargs):
    """Lower `fn` through the SPMD partitioner and audit the compiled
    per-device HLO.  No device execution: ``jit.lower().compile()``
    only — runs fine under JAX_PLATFORMS=cpu with
    --xla_force_host_platform_device_count forced mesh axes.

    example_args: arrays / pytrees / jax.ShapeDtypeStruct placeholders.
    mesh: the jax.sharding.Mesh to partition over.
    in_shardings: 'auto' (dim-0-over-first-axis heuristic via
    auto_shardings), an explicit jit in_shardings tree, or None (let
    jit infer — single-device unless args carry shardings).
    jit_kwargs: full jax.jit kwargs from a compile choke point
    (ParallelTrainer passes its real in/out shardings + donation) —
    overrides in/out_shardings/donate_argnums.
    lower_cache / cache_key: see ``lower_text`` — reuse (or publish)
    the compiled HLO text of this exact (fn, shardings) pair.
    """
    name = name or getattr(fn, '__name__', None) or 'step'
    thr = dict(DEFAULT_HLO_THRESHOLDS)
    thr.update(thresholds or {})
    if jit_kwargs is None:
        jit_kwargs = {}
        if in_shardings == 'auto':
            if mesh is not None:
                sh = auto_shardings(mesh, example_args)
                if sh is not None:
                    jit_kwargs['in_shardings'] = sh
        elif in_shardings is not None:
            jit_kwargs['in_shardings'] = in_shardings
        if out_shardings is not None:
            jit_kwargs['out_shardings'] = out_shardings
        if donate_argnums:
            jit_kwargs['donate_argnums'] = tuple(donate_argnums)
    text = lower_text(fn, *example_args, jit_kwargs=jit_kwargs,
                      lower_cache=lower_cache, cache_key=cache_key,
                      **example_kwargs)
    if global_shapes is None:
        # a caller that already traced the step (the jaxpr lint runs
        # first at every choke point) can pass its shapes and skip
        # this second abstract trace
        global_shapes = _global_big_shapes(
            fn, example_args, example_kwargs, thr['replicated_bytes'])
    return audit_text(text, mesh=mesh, thresholds=thr,
                      disable=disable, global_shapes=global_shapes,
                      name=name)
