"""Built-in audit/planning targets: the lower-and-audit step factored
out of ``tools/tpu_lint.py`` so the ``--hlo`` SPMD audit and the
``--plan`` auto-sharding planner build the SAME step functions with
the SAME sharding resolution — and therefore can share one lowering
per (target, mesh) pair through ``hlo.lower_text``'s cache instead of
paying trace+lower twice when both run.

A *target* is ``builder(mesh) -> (model, example_batch)`` where
``example_batch`` is a tuple of ``jax.ShapeDtypeStruct`` placeholders
(shapes only — nothing here ever touches a device).  The suite
proxies what examples/ + paddle_tpu/models/ actually train: a tiny
GPT in the dp(+tp) posture, the WideDeep sparse-gather model, and the
LeNet vision path.
"""

__all__ = ['TARGETS', 'surrogate_step', 'target_state',
           'batch_shardings', 'cache_key']


def surrogate_step(model, remat=False):
    """forward + scalar surrogate loss + grad wrt params: the comms /
    sharding / liveness story of a train step without dragging a real
    optimizer into the audit.  ``remat=True`` wraps the forward in
    ``jax.checkpoint`` — the planner's remat fallback lowers THIS to
    price what strategy.recompute would buy."""
    import jax
    import jax.numpy as jnp
    from ..jit import functional_call

    def step(params, buffers, key, *batch):
        def loss_fn(p):
            def run(p):
                out, _ = functional_call(model, p, buffers, batch,
                                         key=key, training=True)
                return out
            if remat:
                run = jax.checkpoint(run)
            out = run(p)
            return sum(jnp.square(l.astype(jnp.float32)).mean()
                       for l in jax.tree_util.tree_leaves(out))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        return loss, grads

    return step


def target_state(model, mesh, param_specs=None):
    """(params, buffers) as ShapeDtypeStructs + their shardings.

    ``param_specs`` overrides the model's declared per-param specs
    (``collect_param_shardings``) — the planner passes each candidate
    assignment through here; the default resolution is the same one
    ParallelTrainer does."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.api import collect_param_shardings, make_spec
    params, buffers = model.functional_state()
    specs = param_specs if param_specs is not None \
        else collect_param_shardings(model)
    p_sh = {n: NamedSharding(mesh, make_spec(specs.get(n), v.ndim, mesh))
            for n, v in params.items()}
    repl = NamedSharding(mesh, P())
    b_sh = {n: repl for n in buffers}
    sds = lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype)  # noqa: E731
    return ({n: sds(v) for n, v in params.items()},
            {n: sds(v) for n, v in buffers.items()}, p_sh, b_sh)


def batch_shardings(mesh, batch, axis=None):
    """Shard dim 0 of each batch placeholder over `axis` (default: the
    mesh's first >1 axis) when divisible; replicate otherwise."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if axis is None:
        axis = next((a for a in mesh.axis_names if mesh.shape[a] > 1),
                    None)
    repl = NamedSharding(mesh, P())
    return tuple(
        NamedSharding(mesh, P(axis))
        if axis is not None and b.shape
        and b.shape[0] % mesh.shape[axis] == 0
        else repl
        for b in batch)


def cache_key(target, mesh_axes, param_shardings, batch_shardings,
              remat=False, batch=()):
    """The shared lowering-memo key for one fully-resolved
    (target, mesh, shardings) triple.

    Keyed on the RESOLVED PartitionSpecs, not the assignment name:
    the planner's ``replicated`` candidate on a dp-only mesh resolves
    to the same program as the ``--hlo`` audit's declared-spec
    lowering there, and must hit the same memo entry.  Size-1 axes
    are elided so ``--mesh dp=8`` and the planner's
    ``{'dp': 8, 'tp': 1}`` candidate hash identically."""
    axes = tuple((a, int(s)) for a, s in dict(mesh_axes).items()
                 if int(s) > 1)

    def spec_of(sh):
        spec = getattr(sh, 'spec', sh)
        return str(tuple(spec)) if spec is not None else '()'

    pf = tuple(sorted((n, spec_of(s))
                      for n, s in dict(param_shardings).items()))
    bf = tuple(spec_of(s) for s in batch_shardings)
    shapes = tuple((tuple(b.shape), str(b.dtype)) for b in batch)
    return (str(target), axes, pf, bf, bool(remat), shapes)


def _ids_batch(shape, vocab):
    import jax
    import jax.numpy as jnp
    del vocab     # shapes only: lowering never reads values
    return jax.ShapeDtypeStruct(shape, jnp.int32)


def _target_gpt(mesh):
    """Tiny GPT in the dp(+tp) posture of examples/gpt_train_generate
    and examples/distributed_hybrid."""
    import paddle_tpu as paddle
    from ..models.gpt import GPT, GPTConfig
    del mesh
    paddle.seed(0)
    model = GPT(GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                          num_heads=4, max_seq_len=32, dropout=0.0))
    return model, (_ids_batch((8, 16), 128),)


def _target_widedeep(mesh):
    """WideDeep sparse-gather model (paddle_tpu/models/widedeep)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from ..models.widedeep import WideDeep
    del mesh
    paddle.seed(0)
    model = WideDeep([16, 16, 16, 16], dense_dim=4, embed_dim=8,
                     shard_vocab=False)
    return model, (_ids_batch((8, 4), 16),
                   jax.ShapeDtypeStruct((8, 4), jnp.float32))


def _target_gptserve(mesh):
    """One paged decode step of the serving engine
    (serving/engine.DecodeAuditLayer): a ragged live batch attending
    the paged KV pool through per-sequence block tables — the
    continuous-batching serving surface, auditable/plannable like any
    train step."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from ..models.gpt import GPTConfig, GPTForCausalLM
    from ..serving.engine import DecodeAuditLayer
    del mesh
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        max_seq_len=32, dropout=0.0))
    model.eval()
    L, nh, hd = 2, 4, 16
    S, bs, mb = 8, 8, 4                   # batch, block size, table w
    nb = S * mb + 1                       # pool incl. trash block
    return DecodeAuditLayer(model), (
        _ids_batch((S, 1), 128),
        jax.ShapeDtypeStruct((L, nb, nh, bs, hd), jnp.float32),
        jax.ShapeDtypeStruct((L, nb, nh, bs, hd), jnp.float32),
        _ids_batch((S, mb), 0),
        _ids_batch((S,), 0))


def _target_lenet(mesh):
    """LeNet vision path of examples/mnist_lenet."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from ..vision.models import LeNet
    del mesh
    paddle.seed(0)
    model = LeNet()
    return model, (jax.ShapeDtypeStruct((8, 1, 28, 28), jnp.float32),)


# target name -> builder(mesh) -> (model, example_batch); the suite
# proxies what examples/ + paddle_tpu/models/ actually train
TARGETS = {
    'gpt': _target_gpt,
    'widedeep': _target_widedeep,
    'lenet': _target_lenet,
    'gptserve': _target_gptserve,
}
