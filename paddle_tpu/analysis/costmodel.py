"""Topology-aware cost model for TPU collectives.

Pure math, no jax: given a collective opcode, the per-device buffer
size the compiled (post-SPMD-partitioner) HLO shows, and the mesh-axis
decomposition of its replica group, predict the bytes each device puts
on the ICI wire and a latency-vs-bandwidth time estimate.

Two model shapes:

* ``ring_cost`` — the classic single-ring bound (one flat ring over
  the whole group).  Kept byte-exact for single-axis groups; it is
  the honest estimate when the group does not align with mesh axes.
* ``torus_cost`` — per-axis staging on a 2D/3D torus, which is what
  XLA actually emits for multi-axis replica groups (the distributed
  linear-algebra TPU paper's decomposition, arxiv 2112.09017):

    all-reduce    reduce-scatter down each axis then all-gather back
                  up in reverse.  Wire bytes are unchanged versus the
                  flat ring (2·S·(n-1)/n — the bytes must still
                  leave), but the phase count drops from 2·(n-1) to
                  Σ 2·(a_i - 1): a 4x4 mesh pays 12 hop latencies,
                  not 30.
    all-gather /  per-axis gathers / scatters: (n-1)/n · S on the
    reduce-scatter  wire, Σ (a_i - 1) phases.
    all-to-all    per-axis exchange (store-and-forward): each stage
                  forwards (a_i - 1)/a_i of the FULL buffer along
                  that axis — Σ S·(a_i-1)/a_i wire bytes (MORE than
                  the flat ring's (n-1)/n·S: transit bytes are real)
                  in only Σ (a_i - 1) phases.
    collective-permute  S bytes, 1 hop.

``axes_for_group`` infers the torus decomposition of a replica group
from the active mesh shape, so ``analysis.hlo``'s census stops
costing a dp×tp mesh as one flat ring over all chips.

The time estimate is the alpha+beta sum per stage: phases · per-hop
latency (dominates small buffers) plus stage wire bytes / link
bandwidth (dominates giant grads).  Both knobs are *axis-aware*: pass
a dict ({axis_name: value}, ``'default'`` fallback) when the mesh
wires different generations/directions differently.  A
``Calibration`` table (measured alpha/beta per collective kind,
fitted offline by ``tools/calibrate_costmodel.py`` from archived run
telemetry) overrides the analytic estimate entirely — the planner
(``analysis.planner``) consumes it so ranked plans track the chips
actually in the building rather than data-sheet constants.
"""
import json

__all__ = ['COLLECTIVE_OPS', 'ring_cost', 'torus_cost',
           'axes_for_group', 'Calibration', 'load_calibration',
           'effective_links', 'WIRE_DTYPE_BYTES', 'quant_wire_factor',
           'quantized_allreduce_cost',
           'DEFAULT_LINK_BW_GBPS', 'DEFAULT_LINK_LATENCY_US']

# per-direction ICI link bandwidth and per-hop latency.  ~90 GB/s and
# ~1 us are the right order for one TPU v4/v5 ICI link; both are knobs
# (thresholds / CLI flags / calibration tables) because the point is
# the MODEL SHAPE of the prediction, not chip-generation precision.
DEFAULT_LINK_BW_GBPS = 90.0
DEFAULT_LINK_LATENCY_US = 1.0

COLLECTIVE_OPS = ('all-reduce', 'all-gather', 'reduce-scatter',
                  'all-to-all', 'collective-permute')

CALIBRATION_VERSION = 1


class Calibration:
    """Measured cost-model parameters from a chip session.

    ``per_op`` maps a collective kind to fitted ``alpha_us`` (per hop)
    and ``beta_us_per_byte`` (per wire byte): when present, the
    estimate for that kind becomes ``alpha·phases + beta·wire`` with
    the MEASURED constants.  ``link_bw_gbps`` / ``link_latency_us``
    (scalar or {axis: value}) re-anchor the analytic defaults for
    kinds that were not fitted.  Produced by
    ``tools/calibrate_costmodel.py``; consumed via
    ``tpu_lint --plan --calibration file.json`` and
    ``ParallelTrainer(auto_shard=True, calibration=...)``.
    """

    def __init__(self, per_op=None, link_bw_gbps=None,
                 link_latency_us=None, meta=None):
        self.per_op = dict(per_op or {})
        self.link_bw_gbps = link_bw_gbps
        self.link_latency_us = link_latency_us
        self.meta = dict(meta or {})

    @classmethod
    def from_dict(cls, doc):
        v = doc.get('version', CALIBRATION_VERSION)
        if v > CALIBRATION_VERSION:
            raise ValueError(
                f'calibration table version {v} is newer than this '
                f'cost model understands ({CALIBRATION_VERSION})')
        return cls(per_op=doc.get('per_op'),
                   link_bw_gbps=doc.get('link_bw_gbps'),
                   link_latency_us=doc.get('link_latency_us'),
                   meta=doc.get('meta'))

    def to_dict(self):
        doc = {'version': CALIBRATION_VERSION, 'per_op': self.per_op}
        if self.link_bw_gbps is not None:
            doc['link_bw_gbps'] = self.link_bw_gbps
        if self.link_latency_us is not None:
            doc['link_latency_us'] = self.link_latency_us
        if self.meta:
            doc['meta'] = self.meta
        return doc

    def save(self, path):
        with open(path, 'w') as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)

    def __repr__(self):
        return f'Calibration(per_op={sorted(self.per_op)})'


def load_calibration(path):
    """Read a calibration table written by calibrate_costmodel.py."""
    with open(path) as f:
        return Calibration.from_dict(json.load(f))


def effective_links(bw_gbps, latency_us, calibration):
    """Resolve the link knobs against a calibration table: measured
    link numbers re-anchor the analytic DEFAULTS, while an explicit
    non-default override (CLI flag / thresholds) still wins.  Returns
    (bw_gbps, latency_us), never None."""
    if calibration is not None:
        if calibration.link_bw_gbps is not None and \
                (not bw_gbps or bw_gbps == DEFAULT_LINK_BW_GBPS):
            bw_gbps = calibration.link_bw_gbps
        if calibration.link_latency_us is not None and \
                (not latency_us
                 or latency_us == DEFAULT_LINK_LATENCY_US):
            latency_us = calibration.link_latency_us
    return (bw_gbps or DEFAULT_LINK_BW_GBPS,
            latency_us or DEFAULT_LINK_LATENCY_US)


def _per_axis(value, axis, default):
    """Resolve a scalar-or-{axis: value} knob for one mesh axis."""
    if value is None:
        return default
    if isinstance(value, dict):
        v = value.get(axis)
        if v is None:
            v = value.get('default')
        return default if v is None else float(v)
    return float(value)


def _norm_axes(axes):
    """axes -> ((name_or_None, size>1), ...).  Accepts bare ints,
    (name, size) pairs, or a mix; size-1 axes are elided (nothing
    moves along them)."""
    out = []
    for a in axes:
        if isinstance(a, (tuple, list)):
            name, size = a[0], int(a[1])
        else:
            name, size = None, int(a)
        if size > 1:
            out.append((name, size))
    return tuple(out)


def axes_for_group(mesh_shape, group_size):
    """Infer the torus decomposition of a replica group of
    ``group_size`` devices on a mesh of ``mesh_shape`` (ordered
    {axis: size}).

    XLA forms replica groups along mesh axes, so a group's size is a
    product of some subset of axis sizes; the finest matching subset
    (most axes) is the decomposition torus routing exploits.  Returns
    a tuple of (axis_name, size) pairs, or ``((None, group_size),)``
    — the flat-ring fallback — when no subset multiplies out (a group
    that does not align with the mesh, or no mesh in hand)."""
    n = max(1, int(group_size))
    if n == 1:
        return ()
    sized = [(name, int(s)) for name, s in (mesh_shape or {}).items()
             if int(s) > 1]
    best = None

    def dfs(i, left, picked):
        nonlocal best
        if left == 1:
            if best is None or len(picked) > len(best):
                best = tuple(picked)
            return
        if i == len(sized):
            return
        name, s = sized[i]
        if left % s == 0:
            picked.append((name, s))
            dfs(i + 1, left // s, picked)
            picked.pop()
        dfs(i + 1, left, picked)

    dfs(0, n, [])
    return best if best else ((None, n),)


def _stages(opcode, s, axes):
    """Per-axis (axis_name, phases, wire_bytes) stages of one
    collective, floats for the multi-axis staging math."""
    stages = []
    if opcode == 'collective-permute':
        name = axes[0][0] if axes else None
        return [(name, 1, float(s))]
    if opcode == 'all-reduce':
        remaining = float(s)
        down = []
        for name, a in axes:          # reduce-scatter down each axis
            down.append((name, a - 1, remaining * (a - 1) / a))
            remaining /= a
        # all-gather back up in reverse: mirror bytes and phases
        return down + [st for st in reversed(down)]
    if opcode == 'reduce-scatter':
        remaining = float(s)
        for name, a in axes:
            stages.append((name, a - 1, remaining * (a - 1) / a))
            remaining /= a
        return stages
    if opcode == 'all-gather':
        # s is the GATHERED (output) size; the per-device shard grows
        # axis by axis
        n = 1
        for _, a in axes:
            n *= a
        have = float(s) / n
        for name, a in axes:
            stages.append((name, a - 1, have * (a - 1)))
            have *= a
        return stages
    if opcode == 'all-to-all':
        # store-and-forward: every stage forwards (a-1)/a of the FULL
        # buffer along its axis
        for name, a in axes:
            stages.append((name, a - 1, float(s) * (a - 1) / a))
        return stages
    return []


def torus_cost(opcode, local_bytes, axes, *, bw_gbps=None,
               latency_us=None, calibration=None):
    """Predicted cost of ONE collective over a torus-decomposed group.

    opcode: base HLO opcode (no -start/-done suffix).
    local_bytes: the op's per-device buffer size — the operand for
    all-reduce/reduce-scatter/all-to-all/collective-permute, the
    OUTPUT for all-gather (the gathered buffer).
    axes: the replica group's per-axis sizes — bare ints or
    (axis_name, size) pairs, e.g. ``(('dp', 4), ('tp', 2))`` from
    ``axes_for_group``.  A single axis reduces to the classic ring.
    bw_gbps / latency_us: scalar or {axis_name: value} knobs.
    calibration: optional ``Calibration`` with fitted per-op
    alpha/beta that override the analytic estimate.

    Returns {'wire_bytes', 'phases', 'est_us', 'axes'}; an empty /
    all-1 group (or an unknown opcode) costs nothing — the
    partitioner elides it.
    """
    s = max(0, int(local_bytes))
    axes = _norm_axes(axes)
    if not axes or opcode not in COLLECTIVE_OPS or s == 0:
        return {'wire_bytes': 0, 'phases': 0, 'est_us': 0.0, 'axes': ()}
    bw_gbps, latency_us = effective_links(bw_gbps, latency_us,
                                          calibration)
    if len(axes) == 1 and opcode != 'collective-permute':
        # byte-exact single-ring arithmetic (the pre-torus contract)
        name, n = axes[0]
        if opcode == 'all-reduce':
            wire = 2 * (n - 1) * s // n
            phases = 2 * (n - 1)
        else:   # all-gather / reduce-scatter / all-to-all
            wire = (n - 1) * s // n
            phases = n - 1
        alpha = _per_axis(latency_us, name, DEFAULT_LINK_LATENCY_US)
        bw = _per_axis(bw_gbps, name, DEFAULT_LINK_BW_GBPS)
        est = phases * alpha + wire / (bw * 1e3)
    else:
        stages = _stages(opcode, s, axes)
        phases = sum(p for _, p, _ in stages)
        wire = int(sum(b for _, _, b in stages))
        est = 0.0
        for name, p, b in stages:
            alpha = _per_axis(latency_us, name, DEFAULT_LINK_LATENCY_US)
            bw = _per_axis(bw_gbps, name, DEFAULT_LINK_BW_GBPS)
            # 1 GB/s moves 1e3 bytes per microsecond
            est += p * alpha + b / (bw * 1e3)
    cal = (calibration.per_op.get(opcode)
           if calibration is not None else None)
    if cal:
        est = (float(cal.get('alpha_us', 0.0)) * phases
               + float(cal.get('beta_us_per_byte', 0.0)) * wire)
    return {'wire_bytes': wire, 'phases': phases,
            'est_us': round(est, 3), 'axes': axes}


# -- wire-dtype dimension (quantized collectives, EQuARX) ---------------------

# bytes per element on the wire, keyed by HLO dtype spellings AND the
# quant-config spellings — one table so census rows ('f32', 's8') and
# planner what-ifs ('int8', 'bf16') price identically
WIRE_DTYPE_BYTES = {
    'f64': 8.0, 'f32': 4.0, 'float32': 4.0, 'f16': 2.0, 'bf16': 2.0,
    'bfloat16': 2.0, 's8': 1.0, 'u8': 1.0, 'int8': 1.0,
    'int4': 0.5, 's4': 0.5,
}


def quant_wire_factor(elem_bytes=4, wire_dtype='int8', block=256,
                      scale_bytes=4):
    """Payload-byte multiplier of re-wiring a collective at
    ``wire_dtype``: the quantized element plus one f32 scale per
    ``block`` elements, over the full-width element.  int8 over f32
    with block=256 ≈ 0.254 (the EQuARX ~4x)."""
    qb = WIRE_DTYPE_BYTES.get(wire_dtype)
    if qb is None:
        raise ValueError(f'unknown wire dtype {wire_dtype!r}')
    return (qb + float(scale_bytes) / block) / float(elem_bytes)


def quantized_allreduce_cost(local_bytes, axes, *, elem_bytes=4,
                             wire_dtype='int8', block=256,
                             master_accum=False, bw_gbps=None,
                             latency_us=None, calibration=None):
    """Predicted cost of the DECOMPOSED quantized all-reduce
    (parallel.quant_collectives): quantize → all-to-all → local sum →
    quantize → all-gather, both halves at ``wire_dtype`` payload
    bytes (+ per-block f32 scales).  ``master_accum`` keeps the
    reduce half a full-width reduce-scatter (exact sum) and quantizes
    only the gather.  Returns the torus_cost dict shape plus
    ``wire_dtype`` — the planner's what-if when a full-width
    all-reduce dominates a plan's estimate."""
    f = quant_wire_factor(elem_bytes, wire_dtype, block)
    qbytes = int(local_bytes * f)
    kw = dict(bw_gbps=bw_gbps, latency_us=latency_us,
              calibration=calibration)
    if master_accum:
        first = torus_cost('reduce-scatter', int(local_bytes), axes,
                           **kw)
    else:
        first = torus_cost('all-to-all', qbytes, axes, **kw)
    second = torus_cost('all-gather', qbytes, axes, **kw)
    return {
        'wire_bytes': first['wire_bytes'] + second['wire_bytes'],
        'phases': first['phases'] + second['phases'],
        'est_us': round(first['est_us'] + second['est_us'], 3),
        'axes': second['axes'],
        'wire_dtype': wire_dtype,
    }


def ring_cost(opcode, local_bytes, group_size, *,
              bw_gbps=DEFAULT_LINK_BW_GBPS,
              latency_us=DEFAULT_LINK_LATENCY_US):
    """Flat single-ring bound over the whole group (the honest
    estimate when no mesh decomposition is known).  See torus_cost
    for the semantics of opcode/local_bytes."""
    n = max(1, int(group_size))
    if n == 1:
        return {'wire_bytes': 0, 'phases': 0, 'est_us': 0.0}
    out = torus_cost(opcode, local_bytes, ((None, n),),
                     bw_gbps=bw_gbps, latency_us=latency_us)
    return {'wire_bytes': out['wire_bytes'], 'phases': out['phases'],
            'est_us': out['est_us']}
