"""Ring cost model for TPU collectives (EQuARX-style comms audit).

Pure math, no jax: given a collective opcode, the per-device buffer
size the compiled (post-SPMD-partitioner) HLO shows, and the replica
group size, predict the bytes each device puts on the ICI wire and a
latency-vs-bandwidth time estimate.  The classic ring algorithms XLA
uses on TPU tori:

  all-reduce      reduce-scatter + all-gather: 2·(n-1)/n · S on the
                  wire per device, 2·(n-1) hop phases
  all-gather      each device forwards every shard once: (n-1)·S_shard
                  = (n-1)/n · S_out, n-1 phases
  reduce-scatter  (n-1)/n · S_in, n-1 phases
  all-to-all      (n-1)/n · S, n-1 phases (torus routing folds this,
                  but the ring bound is the honest static estimate)
  collective-permute  S bytes, 1 hop

The time estimate is the max of the latency term (phases · per-hop
latency — dominates small buffers, EQuARX's motivating regime) and the
bandwidth term (wire bytes / link bandwidth — dominates giant grads),
reported as their sum (the usual α+β model upper bound).

`analysis.hlo` drives this over a parsed HLO module; ParallelTrainer's
collective census emits the prediction as a ``collective_cost``
telemetry event so tools/run_report.py can put predicted and observed
traffic side by side.
"""

__all__ = ['COLLECTIVE_OPS', 'ring_cost', 'DEFAULT_LINK_BW_GBPS',
           'DEFAULT_LINK_LATENCY_US']

# per-direction ICI link bandwidth and per-hop latency.  ~90 GB/s and
# ~1 us are the right order for one TPU v4/v5 ICI link; both are knobs
# (thresholds / CLI flags) because the point is the MODEL SHAPE of the
# prediction, not chip-generation precision.
DEFAULT_LINK_BW_GBPS = 90.0
DEFAULT_LINK_LATENCY_US = 1.0

# opcode -> (wire fraction numerator as f(n), phases as f(n)); S is the
# per-device buffer size the compiled HLO shows for the op
COLLECTIVE_OPS = ('all-reduce', 'all-gather', 'reduce-scatter',
                  'all-to-all', 'collective-permute')


def ring_cost(opcode, local_bytes, group_size, *,
              bw_gbps=DEFAULT_LINK_BW_GBPS,
              latency_us=DEFAULT_LINK_LATENCY_US):
    """Predicted cost of ONE collective op.

    opcode: base HLO opcode (no -start/-done suffix).
    local_bytes: the op's per-device buffer size — the operand for
    all-reduce/reduce-scatter/all-to-all/collective-permute, the
    OUTPUT for all-gather (the gathered buffer).
    group_size: devices per replica group (n).

    Returns {'wire_bytes', 'phases', 'est_us'}; a group of 1 (or an
    unknown opcode) costs nothing — the partitioner elides it.
    """
    n = max(1, int(group_size))
    s = max(0, int(local_bytes))
    if n == 1 or opcode not in COLLECTIVE_OPS or s == 0:
        return {'wire_bytes': 0, 'phases': 0, 'est_us': 0.0}
    if opcode == 'all-reduce':
        wire = 2 * (n - 1) * s // n
        phases = 2 * (n - 1)
    elif opcode == 'collective-permute':
        wire = s
        phases = 1
    else:   # all-gather / reduce-scatter / all-to-all
        wire = (n - 1) * s // n
        phases = n - 1
    # alpha-beta model: latency term + bandwidth term.  1 GB/s moves
    # 1e3 bytes per microsecond.
    est_us = phases * float(latency_us) + wire / (float(bw_gbps) * 1e3)
    return {'wire_bytes': wire, 'phases': phases,
            'est_us': round(est_us, 3)}
