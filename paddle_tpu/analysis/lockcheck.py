"""Opt-in runtime lock checker — lockdep-lite for the host runtime.

The static pass (threads.py) sees what the source *says*; this one
sees what the threads actually *do*.  Armed (it is OFF by default),
it patches ``threading.Lock``/``threading.RLock`` so locks
constructed from paddle_tpu frames are wrapped with instrumented
proxies that record, per thread, the stack of currently-held locks:

- every "acquire B while holding A" adds an A→B edge to a lock-order
  graph (nodes are construction sites, lockdep-class style, so all
  instances from one site share a node); a cycle in that graph is a
  potential deadlock even if the run never actually deadlocked —
  reported as a HIGH ``lock-order-cycle`` finding with the
  first-seen acquisition stacks;
- ``guard_object(obj, attrs, lock_attr)`` registers live objects
  whose attributes must only be touched under their lock: any
  cross-thread access while the lock is not held is a HIGH
  ``unguarded-access`` finding (the runtime teeth behind the static
  guarded-by annotations);
- hold times per lock are aggregated and emitted as one ``lockcheck``
  telemetry event when the checker disarms.

Posture: the established opt-in shape — ``install()`` (context
manager / pytest fixture) arms explicitly; ``maybe_install(arg)``
follows resolve_watchdog's contract (explicit ``False`` beats the
env, ``None`` lets ``PADDLE_TPU_LOCKCHECK`` decide).  tier-1 pins the
env to ``0`` (conftest) and the chaos composition test arms it on
purpose.  The checker itself must never deadlock or crash the run:
its one internal mutex is a real (unwrapped) lock, taken only for
short dict updates and never while blocking on a user lock.
"""
import os
import sys
import threading
import time

from contextlib import contextmanager

from .findings import Finding, LintReport, HIGH

__all__ = ['LockChecker', 'CheckedLock', 'install', 'maybe_install',
           'resolve_lockcheck', 'LOCKCHECK_ENV']

LOCKCHECK_ENV = 'PADDLE_TPU_LOCKCHECK'

# the real factories, bound at import time — everything internal to
# the checker (and the restore path) uses these, never the patched
# module attributes
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_OFF_VALUES = ('', '0', 'off', 'false', 'no')


def resolve_lockcheck(arg=None):
    """Shared opt-in posture: explicit False -> off even if the env
    says on; True -> on; None -> PADDLE_TPU_LOCKCHECK decides."""
    if arg is False:
        return False
    if arg is True:
        return True
    return os.environ.get(LOCKCHECK_ENV, '').lower() not in _OFF_VALUES


def _site_name(frame):
    return (f'{os.path.basename(frame.f_code.co_filename)}'
            f':{frame.f_lineno}')


def _short_stack(skip=2, depth=4):
    """Compact acquisition stack: innermost `depth` frames outside
    this module."""
    here = os.path.abspath(__file__)
    out = []
    f = sys._getframe(skip)
    while f is not None and len(out) < depth:
        if os.path.abspath(f.f_code.co_filename) != here:
            out.append(f'{os.path.basename(f.f_code.co_filename)}'
                       f':{f.f_lineno}:{f.f_code.co_name}')
        f = f.f_back
    return ' < '.join(out)


class CheckedLock:
    """Instrumented proxy around a real Lock/RLock.  Mirrors the
    context-manager protocol and forwards everything else (Condition
    internals like ``_is_owned`` included) to the wrapped lock."""

    def __init__(self, real, checker, name):
        self._real = real
        self._checker = checker
        self.name = name

    def acquire(self, blocking=True, timeout=-1):
        got = self._real.acquire(blocking, timeout)
        if got:
            self._checker._note_acquire(self)
        return got

    def release(self):
        self._checker._note_release(self)
        self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        # locked(), _is_owned(), _release_save(), ... — the wrapped
        # lock's own protocol keeps working (Condition over a plain
        # Lock falls back to acquire/release, which stay instrumented)
        return getattr(self._real, name)

    def __repr__(self):
        return f'<CheckedLock {self.name} of {self._real!r}>'


class LockChecker:
    """Lock-order graph + guarded-object registry for one armed
    window."""

    def __init__(self, scope='paddle_tpu', max_findings=200):
        self.scope = scope          # substring filter on the file
        self.locks_created = 0      # constructing Lock()/RLock();
        self.max_findings = max_findings
        self._meta = _REAL_LOCK()   # internal mutex: short updates only
        self._tls = threading.local()
        self._edges = {}            # (a, b) -> first-seen stack pair
        self._hold = {}             # name -> [count, total_s, max_s]
        self._violations = []
        self._vseen = set()
        self._guarded = []          # (obj, original class)

    # -- wrapping -------------------------------------------------------------

    def wrap(self, lock=None, name=None, rlock=False):
        """Wrap an existing lock (or make a fresh one) under a stable
        graph-node name."""
        real = lock if lock is not None else (
            _REAL_RLOCK() if rlock else _REAL_LOCK())
        if name is None:
            name = _site_name(sys._getframe(1))
        self.locks_created += 1
        return CheckedLock(real, self, name)

    def _make_factory(self, rlock):
        checker = self
        real = _REAL_RLOCK if rlock else _REAL_LOCK
        scope = self.scope

        def factory():
            r = real()
            if scope is not None:
                f = sys._getframe(1)
                if scope not in f.f_code.co_filename:
                    return r          # foreign lock: stay invisible
            checker.locks_created += 1
            return CheckedLock(r, checker,
                               _site_name(sys._getframe(1)))
        return factory

    # -- acquisition tracking -------------------------------------------------

    def _held(self):
        h = getattr(self._tls, 'held', None)
        if h is None:
            h = self._tls.held = []
        return h

    def holds(self, lock):
        """Does the calling thread currently hold `lock`?"""
        return any(entry[0] is lock for entry in self._held())

    def _note_acquire(self, lock):
        held = self._held()
        if not self.holds(lock):    # re-entrant RLock: no new edges
            prior = {e[0].name for e in held}
            prior.discard(lock.name)
            new = [(p, lock.name) for p in prior
                   if (p, lock.name) not in self._edges]
            if new:
                stack = _short_stack()
                with self._meta:
                    for edge in new:
                        self._edges.setdefault(edge, stack)
        held.append((lock, time.monotonic()))

    def _note_release(self, lock):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                _, t0 = held.pop(i)
                dt = time.monotonic() - t0
                with self._meta:
                    st = self._hold.setdefault(lock.name,
                                               [0, 0.0, 0.0])
                    st[0] += 1
                    st[1] += dt
                    st[2] = max(st[2], dt)
                return
        # release of a lock acquired before arming: nothing recorded

    # -- guarded objects ------------------------------------------------------

    def guard_object(self, obj, attrs, lock_attr='_lock'):
        """Register a live object: accesses to `attrs` from any
        thread other than the registering one, while `obj.<lock_attr>`
        is not held, become HIGH ``unguarded-access`` findings.
        Undone automatically when the checker disarms."""
        cls = type(obj)
        checker = self
        attrset = frozenset(attrs)
        owner = threading.get_ident()

        def _ga(inner, name):
            if name in attrset:
                checker._check_guarded(inner, name, lock_attr, owner)
            return cls.__getattribute__(inner, name)

        def _sa(inner, name, value):
            if name in attrset:
                checker._check_guarded(inner, name, lock_attr, owner)
            cls.__setattr__(inner, name, value)

        sub = type(cls.__name__, (cls,),
                   {'__getattribute__': _ga, '__setattr__': _sa})
        obj.__class__ = sub
        self._guarded.append((obj, cls))
        return obj

    def _check_guarded(self, obj, attr, lock_attr, owner):
        if threading.get_ident() == owner:
            return                  # cross-thread accesses only
        try:
            lock = object.__getattribute__(obj, lock_attr)
        except AttributeError:
            return
        if isinstance(lock, CheckedLock):
            if self.holds(lock):
                return
        else:
            is_owned = getattr(lock, '_is_owned', None)
            if is_owned is None or is_owned():
                return              # plain Lock: holder unknowable
        # caller site: innermost frame outside this module
        here = os.path.abspath(__file__)
        file, line = None, None
        f = sys._getframe(1)
        while f is not None:
            if os.path.abspath(f.f_code.co_filename) != here:
                file, line = f.f_code.co_filename, f.f_lineno
                break
            f = f.f_back
        key = (type(obj).__name__, attr, file, line)
        with self._meta:
            if key in self._vseen or \
                    len(self._violations) >= self.max_findings:
                return
            self._vseen.add(key)
            self._violations.append(Finding(
                'unguarded-access', HIGH,
                f'{type(obj).__name__}.{attr} accessed from thread '
                f'{threading.current_thread().name!r} without '
                f'holding {lock_attr}',
                file=file, line=line, origin='runtime'))

    def _unguard_all(self):
        for obj, cls in self._guarded:
            try:
                obj.__class__ = cls
            except TypeError:       # pragma: no cover - layout change
                pass
        self._guarded = []

    # -- reporting ------------------------------------------------------------

    def cycles(self):
        """Simple cycles in the lock-order graph (each a node list
        with the closing node repeated), deduped by node set."""
        with self._meta:
            adj = {}
            for a, b in self._edges:
                adj.setdefault(a, []).append(b)
        out, seen_sets = [], set()
        for start in sorted(adj):
            path, on_path = [], set()

            def dfs(n, depth=0):
                if n in on_path:
                    cyc = path[path.index(n):] + [n]
                    key = frozenset(cyc)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append(cyc)
                    return
                if depth > 64:      # graphs here are tiny; stay safe
                    return
                path.append(n)
                on_path.add(n)
                for m in adj.get(n, ()):
                    dfs(m, depth + 1)
                path.pop()
                on_path.discard(n)

            dfs(start)
        return out

    def hold_stats(self):
        with self._meta:
            return {
                name: {'count': c,
                       'total_ms': round(tot * 1e3, 3),
                       'max_ms': round(mx * 1e3, 3)}
                for name, (c, tot, mx) in sorted(self._hold.items())}

    def report(self, name='lockcheck'):
        """LintReport (origin='runtime'): lock-order cycles as HIGH
        potential deadlocks + recorded unguarded accesses, with the
        hold-time stats in extras."""
        rep = LintReport(name=name)
        with self._meta:
            edges = dict(self._edges)
        for cyc in self.cycles():
            stacks = '; '.join(
                f'{a}->{b} @ {edges.get((a, b), "?")}'
                for a, b in zip(cyc, cyc[1:]))
            rep.findings.append(Finding(
                'lock-order-cycle', HIGH,
                'potential deadlock: lock-order cycle '
                + ' -> '.join(cyc)
                + f' (first-seen acquisitions: {stacks})',
                origin='runtime'))
        with self._meta:
            rep.findings.extend(self._violations)
        rep.extras['lockcheck'] = {
            'locks': self.locks_created,
            'edges': len(edges),
            'cycles': len(rep.findings) - len(self._violations),
            'hold': self.hold_stats(),
        }
        return rep

    def emit_telemetry(self):
        """One `lockcheck` event summarizing the armed window."""
        from .. import telemetry
        hold = self.hold_stats()
        worst = sorted(hold.items(), key=lambda kv: -kv[1]['max_ms'])
        telemetry.event(
            'lockcheck',
            locks=self.locks_created, edges=len(self._edges),
            cycles=len(self.cycles()),
            violations=len(self._violations),
            max_hold_ms=(worst[0][1]['max_ms'] if worst else 0.0),
            max_hold_lock=(worst[0][0] if worst else None))


# -- arming -------------------------------------------------------------------

_install_mutex = _REAL_LOCK()
_active = [None]


@contextmanager
def install(scope='paddle_tpu', checker=None, emit=True):
    """Arm the checker: patch threading.Lock/RLock so locks
    constructed (from `scope` frames) inside the window are
    instrumented.  Restores the factories, un-guards registered
    objects, and emits the `lockcheck` telemetry event on exit —
    exceptions included."""
    chk = checker if checker is not None else LockChecker(scope=scope)
    with _install_mutex:
        if _active[0] is not None:
            raise RuntimeError('lockcheck is already installed')
        _active[0] = chk
        threading.Lock = chk._make_factory(rlock=False)
        threading.RLock = chk._make_factory(rlock=True)
    try:
        yield chk
    finally:
        with _install_mutex:
            threading.Lock = _REAL_LOCK
            threading.RLock = _REAL_RLOCK
            _active[0] = None
        chk._unguard_all()
        if emit:
            try:
                chk.emit_telemetry()
            except Exception:       # never crash the guarded run
                pass


@contextmanager
def maybe_install(arg=None, scope='paddle_tpu'):
    """``install()`` when resolve_lockcheck(arg) says on, else a
    no-op context yielding None — the env-gated entry point."""
    if not resolve_lockcheck(arg):
        yield None
        return
    with install(scope=scope) as chk:
        yield chk
