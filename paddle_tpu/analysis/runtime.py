"""Runtime companions to the static rules.

Two hazards are cheaper to catch live than to prove statically:

* ``amp_audit()`` — an eager-mode dtype audit.  It rides the single
  dispatch choke point (core/dispatch.set_audit_hook) and records, for
  every op executed inside the ``with`` block, ops that run with MIXED
  f32 + bf16/f16 array inputs while an auto_cast low-precision region
  is active — the eager twin of the jaxpr ``amp-promotion`` rule.
  Zero overhead when not active (dispatch checks one None).

* ``note_retrace()`` — the recompile monitor the compile caches call
  when one step function accumulates many signature variants
  (hapi.Model / jit.StaticFunction wire this).  Static analysis sees
  one signature; only the runtime sees the cache fork eight times.
"""
import contextlib
import warnings

import jax.numpy as jnp

from .findings import Finding, LintReport, LintWarning, WARN, HIGH

__all__ = ['amp_audit', 'OpDtypeAudit', 'note_retrace']

_LOW = (jnp.bfloat16, jnp.float16)


class OpDtypeAudit:
    """Recorder handed back by amp_audit()."""

    def __init__(self):
        self.ops = []          # (op_name, (dtype, ...)) every op seen
        self.findings = []

    def report(self, name='amp-audit'):
        return LintReport(self.findings, name=name)

    def _observe(self, op_name, vals):
        dtypes = tuple(getattr(v, 'dtype', None) for v in vals)
        self.ops.append((op_name, dtypes))
        from .. import amp as amp_mod
        st = amp_mod.amp_state()
        if not st.enabled or st.dtype not in _LOW:
            return
        if op_name in st.black or op_name in amp_mod.KEEP_LIST:
            return            # f32 here is the policy, not a bug
        has_low = any(d in _LOW for d in dtypes)
        has_f32 = any(d == jnp.float32 for d in dtypes)
        if has_low and has_f32:
            self.findings.append(Finding(
                'amp-promotion', WARN,
                f'op `{op_name}` was fed mixed f32 + low-precision '
                'inputs inside an auto_cast region: the amp hook '
                're-casts the f32 operand on EVERY step (cast + HBM '
                'traffic each time). Cast it once, outside the step '
                '(usually a buffer/constant created outside the '
                'region).',
                origin='runtime'))


@contextlib.contextmanager
def amp_audit():
    """Record eager op dtypes through the dispatch choke point; yields
    an OpDtypeAudit whose .findings hold mixed-precision promotions
    observed inside auto_cast regions."""
    from ..core import dispatch
    audit = OpDtypeAudit()
    prev = dispatch.get_audit_hook()
    dispatch.set_audit_hook(audit._observe)
    try:
        yield audit
    finally:
        dispatch.set_audit_hook(prev)


_warned_retrace = set()


def note_retrace(name, n_variants, threshold=8, instance=None):
    """Called by compile caches when `name` has accumulated
    `n_variants` compiled signatures.  Warns (once per power-of-two
    crossing PER CACHE — pass the owning cache/object as `instance`
    so two models sharing a label don't mask each other) with a
    recompile-hazard finding; returns the Finding when one was
    emitted, else None.

    Every call past the first variant additionally lands a telemetry
    ``retrace`` event + counter, so retraces are COUNTABLE per run
    (run_report) even below the warning threshold — static analysis
    sees one signature; only this monitor sees the cache fork."""
    if n_variants >= 2:
        from .. import telemetry
        telemetry.event('retrace', name=name, variants=n_variants)
        telemetry.add('retrace.count')
    if n_variants < threshold or (n_variants & (n_variants - 1)):
        return None           # warn at threshold, 2x, 4x, ... only
    key = (name, n_variants, id(instance))
    if key in _warned_retrace:
        return None
    _warned_retrace.add(key)
    f = Finding(
        'recompile-hazard', HIGH,
        f'{name} has compiled {n_variants} signature variants: the '
        'step is retracing (varying shapes, Python-scalar args, or '
        'weak/strong dtype flips). Each variant is a full XLA '
        'compile — pad/bucket shapes and pass scalars as arrays.',
        origin='runtime')
    warnings.warn(str(f), LintWarning, stacklevel=3)
    return f
