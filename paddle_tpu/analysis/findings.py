"""Finding / LintReport — the result types of the TPU lint pass.

A Finding is one diagnosed hazard: a rule id (stable, kebab-case — the
thing suppression comments and ``disable=`` lists name), a severity
(``high`` > ``warn`` > ``info``), a human message, and the most precise
source location the analyzer could recover (jaxpr equations carry
source_info; AST findings carry exact lines).  LintReport aggregates
findings for one lint run and renders them for humans (str), machines
(to_json) and gates (max_severity / raise_for).
"""
import json

__all__ = ['Finding', 'LintReport', 'LintError', 'LintWarning',
           'HIGH', 'WARN', 'INFO', 'SEVERITIES']

HIGH = 'high'
WARN = 'warn'
INFO = 'info'
SEVERITIES = (INFO, WARN, HIGH)
_ORDER = {INFO: 0, WARN: 1, HIGH: 2}


class LintWarning(UserWarning):
    """Category used when findings are emitted as warnings — lets users
    ``warnings.filterwarnings`` the lint stream independently."""


class LintError(RuntimeError):
    """Raised by emit(mode='error') / LintReport.raise_for when
    findings at or above the gating severity exist."""

    def __init__(self, message, report=None):
        super().__init__(message)
        self.report = report


class Finding:
    """One diagnosed hazard."""

    __slots__ = ('rule', 'severity', 'message', 'file', 'line', 'origin')

    def __init__(self, rule, severity, message, file=None, line=None,
                 origin='jaxpr'):
        assert severity in SEVERITIES, severity
        self.rule = rule
        self.severity = severity
        self.message = message
        self.file = file
        self.line = line
        self.origin = origin    # 'jaxpr' | 'ast' | 'runtime'

    @property
    def location(self):
        if self.file and self.line:
            return f'{self.file}:{self.line}'
        return self.file or ''

    def to_dict(self):
        return {'rule': self.rule, 'severity': self.severity,
                'message': self.message, 'file': self.file,
                'line': self.line, 'origin': self.origin}

    def __str__(self):
        loc = self.location
        loc = f'{loc}: ' if loc else ''
        return f'[{self.severity}] {self.rule}: {loc}{self.message}'

    def __repr__(self):
        return f'Finding({self!s})'


def _rank(sev):
    return _ORDER[sev]


class LintReport:
    """Findings of one lint run (one step function / one file set)."""

    # set by analysis.lint when a jaxpr was traced; the mesh-gated HLO
    # escalation is the only reader, so the walk it performs must not
    # run on the common single-device path
    _big_shapes_thunk = None
    _big_shapes_cache = None

    def __init__(self, findings=None, name=None):
        self.findings = list(findings or [])
        self.name = name
        # structured side data a pass wants to surface beyond
        # findings (the HLO audit's collective census / peak-memory
        # summary) — rendered by tpu_lint --hlo, part of to_json
        self.extras = {}

    @property
    def global_big_shapes(self):
        """Global traced shapes above the replicated-giant threshold,
        computed on first access (lint_hlo(global_shapes=...) joins
        against these instead of re-tracing).  Raises AttributeError
        when no jaxpr was traced, preserving the getattr(..., None)
        contract at the choke points."""
        if self._big_shapes_thunk is None:
            raise AttributeError('global_big_shapes')
        if self._big_shapes_cache is None:
            self._big_shapes_cache = self._big_shapes_thunk()
        return self._big_shapes_cache

    # -- aggregation ---------------------------------------------------------
    def extend(self, more):
        if isinstance(more, LintReport):
            self.findings.extend(more.findings)
            if more.extras:
                self.extras.update(more.extras)
        else:
            self.findings.extend(more)
        return self

    def at_least(self, severity):
        """Findings at or above `severity`."""
        k = _rank(severity)
        return [f for f in self.findings if _rank(f.severity) >= k]

    @property
    def high(self):
        return [f for f in self.findings if f.severity == HIGH]

    @property
    def warnings(self):
        return [f for f in self.findings if f.severity == WARN]

    @property
    def max_severity(self):
        if not self.findings:
            return None
        return max((f.severity for f in self.findings), key=_rank)

    def __bool__(self):
        return bool(self.findings)

    def __len__(self):
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    # -- gates ---------------------------------------------------------------
    def raise_for(self, severity=HIGH):
        """Raise LintError when findings at/above `severity` exist."""
        bad = self.at_least(severity)
        if bad:
            raise LintError(self.render(bad), report=self)
        return self

    # -- rendering -----------------------------------------------------------
    def counts(self):
        c = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            c[f.severity] += 1
        return c

    def summary(self):
        c = self.counts()
        head = f'tpu-lint[{self.name}]' if self.name else 'tpu-lint'
        if not self.findings:
            return f'{head}: clean'
        return (f'{head}: {c[HIGH]} high, {c[WARN]} warn, '
                f'{c[INFO]} info')

    def render(self, findings=None):
        fs = self.findings if findings is None else findings
        lines = [self.summary()]
        lines += [f'  {f}' for f in sorted(
            fs, key=lambda f: -_rank(f.severity))]
        return '\n'.join(lines)

    def __str__(self):
        return self.render()

    def to_json(self, indent=None):
        doc = {
            'name': self.name,
            'counts': self.counts(),
            'findings': [f.to_dict() for f in self.findings],
        }
        if self.extras:
            doc['extras'] = self.extras
        return json.dumps(doc, indent=indent)
