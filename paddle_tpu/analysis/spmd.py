"""SPMD contract lint — cross-rank divergence checks over the AST + HLO.

The SPMD contract: every rank issues the *same* collective sequence
with the same payload metadata (op, shape, dtype, axes).  Violations
are the two worst multi-host failure classes:

* a **rank-gated collective** (``if rank == 0: allreduce(...)``)
  deadlocks — the gated ranks wait forever for a frame that never
  arrives, and the watchdog can only report a generic timeout;
* **host nondeterminism** (time, env, host ``random``, set/dict
  iteration order) feeding traced values or collective payloads makes
  ranks compute different programs/values — the ``rank_divergence``
  cause class that PR-15 can detect but not attribute.

Rules (``tools/tpu_lint.py --spmd <paths>``):

``rank-dependent-collective`` (HIGH)
    Control flow conditioned on ``process_index``/rank/trainer-id/env
    guards a collective call site so it is reachable on only one side
    of the branch.  Symmetric role splits (``if rank == src: post
    else: fetch``) are the transport idiom and are not flagged —
    ``post``/``fetch`` are two roles of one logical collective.

``collective-order`` (WARN ast / HIGH hlo)
    Per-path collective sequence extraction through a function (AST)
    or HLO ``conditional``: all paths must issue identical
    (op, shape, dtype, axes) sequences.  The HLO half registers into
    the ``--hlo`` audit registry and joins ``hlo.collective_instrs``.

``host-nondeterminism-into-trace`` (HIGH)
    ``time.*``/``os.environ``/host ``random``/``os.getpid``/set
    iteration feeding a collective payload (HIGH — ranks exchange
    different values) or a traced constant via ``jnp.asarray`` (WARN —
    per-rank traces diverge, retrace storms + value splits).
    Sanitizer: routing the value through ``broadcast_object`` (every
    rank receives the src rank's value).

``unbroadcast-rng`` (WARN)
    Host-local entropy (time/pid/urandom/host random) seeding
    ``PRNGKey`` — every rank gets a *different* key stream where the
    replicated-parameter contract expects the same one.  Derive
    per-rank keys from a broadcast base key + ``fold_in(rank)``.

Suppression: ``# tpu-lint: disable=rule-id`` on the finding line or
the enclosing ``def`` line, same grammar as every other lint family.
"""
import ast

from .findings import Finding, LintReport, HIGH, WARN, INFO
from .ast_lint import (
    _is_suppressed, _def_spans, _enclosing_def_lines, _dotted_last)

__all__ = [
    'SPMD_RULES', 'register_spmd_rule',
    'lint_spmd_source', 'lint_spmd_file', 'lint_spmd_sources',
    'HOST_COLLECTIVE_OPS', 'DEVICE_COLLECTIVE_OPS',
]

# -- what counts as a collective ---------------------------------------------

# HostCollectives methods (and the module-level wrappers around them).
# ``post``/``fetch`` are the two roles of one KV-framed collective, so
# sequence comparison normalizes them to one label: a branch that posts
# while the other fetches is the broadcast idiom, not a divergence.
HOST_COLLECTIVE_OPS = frozenset({
    'allreduce', 'allgather', 'allgather_object', 'broadcast_object',
    'barrier_host', '_exchange', 'post', 'fetch',
})

# In-trace (lax / shard_map) collectives.
DEVICE_COLLECTIVE_OPS = frozenset({
    'psum', 'pmean', 'pmax', 'pmin', 'all_gather', 'ppermute',
    'all_to_all', 'psum_scatter', 'pgather',
})

_ALL_COLLECTIVE_OPS = HOST_COLLECTIVE_OPS | DEVICE_COLLECTIVE_OPS

# Explicitly NOT collectives: the non-blocking stats side channel and
# read-only peers.  Listed so the distinction is greppable.
_NON_COLLECTIVE = frozenset({
    'post_stats', 'read_stats', 'read_all_stats', 'read_heartbeats',
})

# Names whose value is rank identity.
_RANK_NAMES = frozenset({
    'rank', 'local_rank', 'process_index', 'trainer_id', 'proc_id',
    'worker_id', 'host_id', 'task_id',
})
_RANK_ENV_TOKENS = ('RANK', 'TRAINER_ID', 'PROCESS', 'WORKER_ID',
                    'TASK_INDEX')

SPMD_RULES = {}


def register_spmd_rule(rule_id, severity):
    def deco(fn):
        SPMD_RULES[rule_id] = (severity, fn)
        return fn
    return deco


# -- shared AST helpers -------------------------------------------------------

class _FuncScope:
    __slots__ = ('node', 'cls', 'start', 'end')

    def __init__(self, node, cls):
        self.node = node
        self.cls = cls
        self.start = node.lineno
        self.end = getattr(node, 'end_lineno', node.lineno)


class _Ctx:
    """Parsed source + per-function index for one file."""

    def __init__(self, tree, src, filename):
        self.tree = tree
        self.src = src
        self.filename = filename
        self.funcs = []
        self._index(tree.body, None)

    def _index(self, body, cls):
        for node in body:
            if isinstance(node, ast.ClassDef):
                self._index(node.body, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs.append(_FuncScope(node, cls))
                self._index(node.body, cls)
            elif isinstance(node, (ast.If, ast.For, ast.While, ast.With,
                                   ast.Try)):
                for field in ('body', 'orelse', 'finalbody'):
                    self._index(getattr(node, field, []) or [], cls)
                for h in getattr(node, 'handlers', []) or []:
                    self._index(h.body, cls)


def _walk_skip_defs(node):
    """ast.walk over `node` without descending into nested defs."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _collective_label(node):
    """The collective op label for a Call node, or None.

    post/fetch normalize to 'post/fetch' so the src/dst role split of
    one logical broadcast compares equal across branches.
    """
    if not isinstance(node, ast.Call):
        return None
    name = _dotted_last(node.func)
    if name in _NON_COLLECTIVE:
        return None
    if name in _ALL_COLLECTIVE_OPS:
        return 'post/fetch' if name in ('post', 'fetch') else name
    return None


def _collectives_in(nodes):
    """(line, label) pairs for collective calls under `nodes`, in
    source order, skipping nested function bodies."""
    out = []
    for root in nodes:
        for n in _walk_skip_defs(root):
            lab = _collective_label(n)
            if lab is not None:
                out.append((n.lineno, lab))
        lab = _collective_label(root)
        if lab is not None:
            out.append((root.lineno, lab))
    out.sort()
    return out


def _is_rank_expr(node):
    """True when the expression's value derives from rank identity."""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in _RANK_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RANK_NAMES:
            return True
        if isinstance(n, ast.Call):
            fname = _dotted_last(n.func)
            if fname in ('process_index', 'process_count'):
                # process_count() alone is replicated; only the index
                # diverges — but count rarely appears in guards alone.
                if fname == 'process_index':
                    return True
            if fname in ('getenv', 'get') or isinstance(n.func, ast.Name):
                for a in n.args:
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        if any(t in a.value for t in _RANK_ENV_TOKENS):
                            return True
        if isinstance(n, ast.Subscript):
            # os.environ['PADDLE_TRAINER_ID']
            sl = n.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                if any(t in sl.value for t in _RANK_ENV_TOKENS):
                    return True
    return False


def _terminates(body):
    """True when the statement list always leaves the function/loop."""
    for stmt in body:
        if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                             ast.Continue)):
            return True
    return False


def _cond_text(node, src_lines):
    try:
        return ast.unparse(node).strip()[:60]
    except Exception:
        line = getattr(node, 'lineno', None)
        if line and 0 < line <= len(src_lines):
            return src_lines[line - 1].strip()[:60]
        return '<cond>'


# -- rule: rank-dependent-collective ------------------------------------------

def _loop_targets(fn):
    """Names bound as For-loop targets inside `fn` — comparing rank
    against one of these (``for r in range(world): if r == self.rank``)
    is the symmetric per-peer iteration every rank runs identically,
    not a rank gate."""
    out = set()
    for n in _walk_skip_defs(fn):
        if isinstance(n, ast.For):
            tgt = n.target
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
            elif isinstance(tgt, ast.Tuple):
                out.update(e.id for e in tgt.elts
                           if isinstance(e, ast.Name))
    return out


def _compares_loop_var(test, loop_names):
    if not isinstance(test, ast.Compare):
        return False
    for side in (test.left, *test.comparators):
        if isinstance(side, ast.Name) and side.id in loop_names:
            return True
    return False


@register_spmd_rule('rank-dependent-collective', HIGH)
def check_rank_dependent_collective(ctx, findings):
    src_lines = ctx.src.splitlines()
    for scope in ctx.funcs:
        fn = scope.node
        loop_names = _loop_targets(fn)
        for node in _walk_skip_defs(fn):
            if not isinstance(node, ast.If):
                continue
            if not _is_rank_expr(node.test):
                continue
            if _compares_loop_var(node.test, loop_names):
                continue    # symmetric per-peer iteration
            body_seq = _collectives_in(node.body)
            else_seq = _collectives_in(node.orelse)
            cond = _cond_text(node.test, src_lines)
            # Early-return gate: `if rank != 0: return` makes every
            # collective after the If one-sided.
            if _terminates(node.body) and not node.orelse:
                end = getattr(node, 'end_lineno', node.lineno)
                after = [(ln, lab) for (ln, lab)
                         in _collectives_in(fn.body) if ln > end]
                if after and not body_seq:
                    ln, lab = after[0]
                    findings.append(Finding(
                        'rank-dependent-collective', HIGH,
                        f'collective `{lab}` only reachable on ranks '
                        f'where `{cond}` is false (guard at line '
                        f'{node.lineno} returns early) — gated ranks '
                        f'never issue it: deadlock hazard',
                        file=ctx.filename, line=ln, origin='ast'))
                    continue
                if after and body_seq:
                    # both paths collect — fall through to sequence
                    # comparison below with `after` as the else path
                    else_seq = after
            if body_seq and not else_seq:
                ln, lab = body_seq[0]
                findings.append(Finding(
                    'rank-dependent-collective', HIGH,
                    f'collective `{lab}` reachable only when `{cond}` '
                    f'— other ranks never issue it: deadlock hazard '
                    f'(hoist it out of the rank guard, or use '
                    f'broadcast_object for one-rank work)',
                    file=ctx.filename, line=ln, origin='ast'))
            elif else_seq and not body_seq:
                ln, lab = else_seq[0]
                findings.append(Finding(
                    'rank-dependent-collective', HIGH,
                    f'collective `{lab}` reachable only when `{cond}` '
                    f'is false — gated ranks never issue it: deadlock '
                    f'hazard',
                    file=ctx.filename, line=ln, origin='ast'))
            elif body_seq and else_seq:
                if [l for _, l in body_seq] != [l for _, l in else_seq]:
                    ln, lab = body_seq[0]
                    findings.append(Finding(
                        'rank-dependent-collective', WARN,
                        f'branches of rank guard `{cond}` issue '
                        f'different collective sequences '
                        f'({[l for _, l in body_seq]} vs '
                        f'{[l for _, l in else_seq]}) — every rank '
                        f'must issue the same sequence',
                        file=ctx.filename, line=ln, origin='ast'))


# -- rule: collective-order (AST half) ----------------------------------------

@register_spmd_rule('collective-order', WARN)
def check_collective_order(ctx, findings):
    src_lines = ctx.src.splitlines()
    for scope in ctx.funcs:
        for node in _walk_skip_defs(scope.node):
            if not isinstance(node, ast.If):
                continue
            if _is_rank_expr(node.test):
                continue  # rank-dependent-collective owns rank guards
            body_seq = [l for _, l in _collectives_in(node.body)]
            else_seq = [l for _, l in _collectives_in(node.orelse)]
            if body_seq and else_seq and body_seq != else_seq:
                cond = _cond_text(node.test, src_lines)
                findings.append(Finding(
                    'collective-order', WARN,
                    f'branches of `{cond}` issue different collective '
                    f'sequences ({body_seq} vs {else_seq}) — if the '
                    f'predicate can disagree across ranks this '
                    f'deadlocks; hoist the collectives or make the '
                    f'predicate replicated',
                    file=ctx.filename, line=node.lineno, origin='ast'))


# -- rule: host-nondeterminism-into-trace -------------------------------------

_TIME_FNS = frozenset({'time', 'time_ns', 'monotonic', 'monotonic_ns',
                       'perf_counter', 'perf_counter_ns'})
_ENTROPY_FNS = frozenset({'getpid', 'urandom', 'uuid1', 'uuid4',
                          'gethostname', 'token_bytes', 'token_hex',
                          'randbytes'})
_HOST_RANDOM_FNS = frozenset({'random', 'randint', 'randrange',
                              'uniform', 'normal', 'rand', 'randn',
                              'choice', 'shuffle', 'sample', 'seed'})
_TRACE_CASTS = frozenset({'asarray', 'array'})
# Sinks whose payload every rank must agree on.  broadcast_object is
# deliberately absent: it is the sanitizer (src rank's value wins).
_PAYLOAD_SINKS = frozenset({'allreduce', 'allgather', 'allgather_object',
                            'post', '_exchange'}) | DEVICE_COLLECTIVE_OPS


def _nondet_source(node):
    """('kind', line) when the expression reads host nondeterminism."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = _dotted_last(n.func)
            base = None
            if isinstance(n.func, ast.Attribute):
                v = n.func.value
                base = v.id if isinstance(v, ast.Name) else \
                    getattr(v, 'attr', None)
            if name in _TIME_FNS and base in ('time', None):
                return ('time.%s()' % name, n.lineno)
            if name in _ENTROPY_FNS:
                return ('%s()' % name, n.lineno)
            if name in _HOST_RANDOM_FNS and base in ('random', 'np',
                                                     'numpy'):
                return ('%s.%s()' % (base, name), n.lineno)
    return None


def _is_broadcast_call(node):
    return (isinstance(node, ast.Call) and
            _dotted_last(node.func) in ('broadcast_object', 'broadcast'))


@register_spmd_rule('host-nondeterminism-into-trace', HIGH)
def check_host_nondeterminism(ctx, findings):
    for scope in ctx.funcs:
        fn = scope.node
        tainted = {}    # name -> source description
        # seed taint from set-iteration (hash-order differs per process
        # under per-process hash randomization)
        for node in _walk_skip_defs(fn):
            if isinstance(node, ast.For) and isinstance(node.target,
                                                        ast.Name):
                it = node.iter
                if isinstance(it, ast.Call) and \
                        _dotted_last(it.func) == 'set':
                    tainted[node.target.id] = 'set(...) iteration order'
        # fixed-point taint propagation through assignments
        # source order approximates flow order: a later
        # `x = broadcast_object(x)` must win over the earlier taint
        assigns = sorted(
            (n for n in _walk_skip_defs(fn)
             if isinstance(n, (ast.Assign, ast.AnnAssign,
                               ast.AugAssign))),
            key=lambda n: n.lineno)
        for _ in range(4):
            changed = False
            for a in assigns:
                value = a.value
                if value is None:
                    continue
                targets = a.targets if isinstance(a, ast.Assign) \
                    else [a.target]
                names = [t.id for t in targets
                         if isinstance(t, ast.Name)]
                if not names:
                    continue
                if _is_broadcast_call(value):
                    for nm in names:        # sanitized
                        if nm in tainted:
                            del tainted[nm]
                            changed = True
                    continue
                src = _nondet_source(value)
                if src is None:
                    for n in ast.walk(value):
                        if isinstance(n, ast.Name) and n.id in tainted:
                            src = (tainted[n.id], value.lineno)
                            break
                if src is not None:
                    for nm in names:
                        if nm not in tainted:
                            tainted[nm] = src[0]
                            changed = True
            if not changed:
                break
        # sinks
        for node in _walk_skip_defs(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted_last(node.func)
            if name in _PAYLOAD_SINKS:
                sev, what = HIGH, 'collective payload'
            elif name in _TRACE_CASTS:
                sev, what = WARN, 'traced value'
            else:
                continue
            args = list(node.args)
            if name in ('post', '_exchange') and len(args) >= 3:
                args = args[2:]     # (tag, op, payload...)
            for arg in args:
                src = _nondet_source(arg)
                if src is None:
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name) and n.id in tainted:
                            src = (tainted[n.id], n.lineno)
                            break
                if src is not None:
                    findings.append(Finding(
                        'host-nondeterminism-into-trace', sev,
                        f'host nondeterminism ({src[0]}) feeds a '
                        f'{what} via `{name}` — ranks will disagree; '
                        f'route it through broadcast_object first',
                        file=ctx.filename, line=node.lineno,
                        origin='ast'))
                    break


# -- rule: unbroadcast-rng ----------------------------------------------------

@register_spmd_rule('unbroadcast-rng', WARN)
def check_unbroadcast_rng(ctx, findings):
    for scope in ctx.funcs:
        fn = scope.node
        tainted = set()
        # source order, so a later sanitizing reassignment wins
        for node in sorted(
                (n for n in _walk_skip_defs(fn)
                 if isinstance(n, ast.Assign)),
                key=lambda n: n.lineno):
            if node.value is not None:
                if _is_broadcast_call(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tainted.discard(t.id)
                    continue
                if _nondet_source(node.value) is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)
        for node in _walk_skip_defs(fn):
            if not isinstance(node, ast.Call):
                continue
            if _dotted_last(node.func) != 'PRNGKey':
                continue
            for arg in node.args:
                bad = _nondet_source(arg)
                if bad is None:
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name) and n.id in tainted:
                            bad = (n.id, n.lineno)
                            break
                if bad is not None:
                    findings.append(Finding(
                        'unbroadcast-rng', WARN,
                        f'PRNGKey seeded from host-local entropy '
                        f'({bad[0]}) — every rank gets a different '
                        f'key; broadcast a base seed then '
                        f'fold_in(rank) for per-rank streams',
                        file=ctx.filename, line=node.lineno,
                        origin='ast'))
                    break


# -- HLO half: collective-order through `conditional` -------------------------

def _register_hlo_half():
    try:
        from .hlo import register_hlo_rule, _collective_base
    except Exception:        # pragma: no cover - hlo always importable
        return

    def _branch_signature(module, comp_name, memo):
        """Ordered (op, shape, group_size) collective signature of a
        computation, recursing through calls (not fusions)."""
        if comp_name in memo:
            return memo[comp_name]
        memo[comp_name] = []        # cycle guard
        comp = module.computations.get(comp_name)
        sig = []
        if comp is not None:
            for ins in comp.instrs:
                base = _collective_base(ins.opcode)
                if base is not None:
                    sig.append((base, ins.type_spec or '',
                                ins.group_size or 0))
                elif ins.opcode in ('call', 'while', 'conditional'):
                    for sub in ins.called:
                        sig.extend(_branch_signature(module, sub, memo))
        memo[comp_name] = sig
        return sig

    @register_hlo_rule('collective-order', HIGH)
    def check_hlo_collective_order(ctx):
        findings = []
        module = ctx.module
        memo = {}
        for comp in module.computations.values():
            for ins in comp.instrs:
                if ins.opcode != 'conditional' or len(ins.called) < 2:
                    continue
                sigs = [(_branch_signature(module, b, memo), b)
                        for b in ins.called]
                first, first_name = sigs[0]
                for sig, name in sigs[1:]:
                    if sig != first:
                        one_sided = (not sig) != (not first)
                        sev = HIGH if one_sided else WARN
                        findings.append(Finding(
                            'collective-order', sev,
                            f'conditional `{ins.name}` branches issue '
                            f'different collective sequences: '
                            f'`{first_name}` -> '
                            f'{[s[0] for s in first] or "none"}, '
                            f'`{name}` -> '
                            f'{[s[0] for s in sig] or "none"} — all '
                            f'paths must issue identical collectives '
                            f'or divergent predicates deadlock',
                            file=ins.file, line=ins.line,
                            origin='hlo'))
                        break
        return findings


_register_hlo_half()


# -- entry points -------------------------------------------------------------

def lint_spmd_source(src, filename='<string>', disable=(),
                     apply_suppress=True):
    """Run the SPMD rules over one source string -> [Finding]."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding('parse-error', INFO, f'could not parse: {e}',
                        file=filename, line=getattr(e, 'lineno', None),
                        origin='ast')]
    ctx = _Ctx(tree, src, filename)
    findings = []
    for rule_id, (severity, fn) in SPMD_RULES.items():
        if rule_id in disable:
            continue
        fn(ctx, findings)
    if apply_suppress:
        spans = _def_spans(tree)
        findings = [
            f for f in findings
            if not _is_suppressed(f.rule, filename, f.line,
                                  _enclosing_def_lines(spans, f.line))]
    findings.sort(key=lambda f: (f.line or 0))
    return findings


def lint_spmd_file(path, disable=()):
    with open(path, encoding='utf-8', errors='replace') as fh:
        return lint_spmd_source(fh.read(), filename=path,
                                disable=disable)


def lint_spmd_sources(paths, disable=()):
    """Lint every .py under `paths` -> LintReport."""
    from .threads import _iter_py_files
    rep = LintReport(name='spmd')
    n_files = 0
    for path in _iter_py_files(paths):
        n_files += 1
        rep.extend(lint_spmd_file(path, disable=disable))
    rep.extras['spmd'] = {'files': n_files,
                          'rules': sorted(SPMD_RULES)}
    return rep
