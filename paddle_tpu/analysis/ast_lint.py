"""AST pre-trace linter for dy2static sources + suppression comments.

The jaxpr rules see what XLA will compile; this pass sees what the
tracer will *choke on or silently sync over* before a jaxpr exists:
``float(loss)`` / ``np.asarray(x)`` / ``x.numpy()`` / ``x.item()``
on traced values force a device→host round trip per step (or raise a
TracerConversion error under jit).  It runs on plain source text — no
imports, no execution — so the CLI can sweep whole directories
(the tier-1 self-lint gate over examples/ and models/ does exactly
that).

Scope
-----
``scope='traced'`` (default) lints only code the framework will
trace: functions decorated with ``to_static``/``jit``/``pjit``,
``forward`` methods of Layer subclasses, functions passed by name to
a ``jit(...)`` call, and everything nested inside those.  ``'all'``
lints every function — the audit mode for step-loop host code (this
is the mode that flagged the per-step ``float(loss)`` in
hapi/model.py's train_batch, fixed in the same PR that added it).

In the DIRECTORY sweep (``lint_file``/``lint_sources``, i.e.
``tpu_lint --scope all``), 'all' is loop-aware for host code: a sync
in a function the framework will trace stays HIGH, but in plain host
functions only syncs inside a ``for``/``while`` body are surfaced as
WARN (a per-iteration host sync in a step loop — the thing the sweep
hunts) and syncs outside loops demote to INFO (boundary
materialization: benches/tests reading back results is how host code
is supposed to look).  ``lint_source``'s raw behavior is unchanged
unless ``host_audit=True`` — lint_callable treats its one function as
traced regardless.

Suppression
-----------
``# tpu-lint: disable=rule-a,rule-b`` (or bare ``disable`` for all
rules) on the finding's line — or on the enclosing ``def`` line to
suppress for a whole function.  The same comments suppress jaxpr-rule
findings whose source location lands on the commented line
(apply_suppressions).
"""
import ast
import linecache
import re

from .findings import Finding, HIGH, WARN, INFO

__all__ = ['lint_source', 'lint_file', 'lint_callable',
           'apply_suppressions', 'suppressed_rules_on_line']

_TRACED_DECORATORS = {'to_static', 'jit', 'pjit'}
_NUMPY_MODULES = {'np', 'numpy', 'onp'}
_NUMPY_SYNC_FUNCS = {'asarray', 'array'}
_TENSOR_SYNC_METHODS = {'numpy', 'item', 'tolist'}
_BUILTIN_CASTS = {'float', 'int', 'bool'}

_SUPPRESS_RE = re.compile(
    r'#\s*tpu-lint:\s*disable(?:=([A-Za-z0-9_,-]+))?')


def suppressed_rules_on_line(file, line):
    """Set of rule ids disabled by a comment on `file`:`line`
    (``{'*'}`` when the bare form disables everything); empty set when
    no comment."""
    if not file or not line:
        return set()
    text = linecache.getline(file, line)
    m = _SUPPRESS_RE.search(text)
    if not m:
        return set()
    if m.group(1) is None:
        return {'*'}
    return {r.strip() for r in m.group(1).split(',') if r.strip()}


def _is_suppressed(rule, file, line, extra_lines=()):
    for ln in (line,) + tuple(extra_lines):
        rules = suppressed_rules_on_line(file, ln)
        if '*' in rules or rule in rules:
            return True
    return False


def apply_suppressions(findings):
    """Drop findings whose source line (in the real file) carries a
    matching ``# tpu-lint: disable`` comment."""
    return [f for f in findings
            if not _is_suppressed(f.rule, f.file, f.line)]


def _dotted_last(node):
    """Last attribute segment of a decorator/callable expression."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _base_names(cls):
    out = []
    for b in cls.bases:
        n = _dotted_last(b)
        if n:
            out.append(n)
    return out


class _Scoper(ast.NodeVisitor):
    """Collect the set of FunctionDef nodes considered 'traced'."""

    def __init__(self, tree):
        self.traced = set()
        self._jit_arg_names = set()
        # pass 1: names handed to jit(...) calls anywhere
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    _dotted_last(node.func) in ('jit', 'to_static'):
                for a in node.args[:1]:
                    if isinstance(a, ast.Name):
                        self._jit_arg_names.add(a.id)
        # pass 2: mark defs
        self._visit_block(tree.body, in_layer=False)

    def _visit_block(self, body, in_layer):
        for node in body:
            if isinstance(node, ast.ClassDef):
                layer = any(b in ('Layer', 'Module')
                            for b in _base_names(node)) or \
                    any(b.endswith('Layer') for b in _base_names(node))
                self._visit_block(node.body, in_layer=layer)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                decs = {_dotted_last(d) for d in node.decorator_list}
                if (decs & _TRACED_DECORATORS
                        or node.name in self._jit_arg_names
                        or (in_layer and node.name == 'forward')):
                    self.traced.add(node)
                    self._mark_nested(node)
                else:
                    self._visit_block(node.body, in_layer=False)

    def _mark_nested(self, fn):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.traced.add(node)


def _def_spans(tree):
    """(def_line, end_line) of every function definition in `tree` —
    the lines whose ``# tpu-lint: disable`` comments suppress findings
    anywhere inside that function (nested defs included; FunctionDef
    .lineno is the `def` keyword's line, not a decorator's)."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans.append((node.lineno,
                          getattr(node, 'end_lineno', node.lineno)))
    return spans


def _enclosing_def_lines(spans, line):
    return tuple(s for s, e in spans if line is not None and
                 s <= line <= e)


def _plausibly_traced_arg(node):
    """Would this expression plausibly hold a tensor?  Literals and
    builtin calls (len(xs), float('nan')) are excluded; names,
    attributes, indexing, arithmetic and METHOD calls (x.mean()) are
    plausible."""
    if isinstance(node, (ast.Name, ast.Attribute, ast.Subscript)):
        return True
    if isinstance(node, ast.BinOp):
        return (_plausibly_traced_arg(node.left)
                or _plausibly_traced_arg(node.right))
    if isinstance(node, ast.UnaryOp):
        return _plausibly_traced_arg(node.operand)
    if isinstance(node, ast.Call) and isinstance(node.func,
                                                 ast.Attribute):
        return True        # method call: x.mean(), loss.sum(), ...
    return False


def _check_call(node, findings, filename):
    fname = _dotted_last(node.func)
    line = node.lineno
    # float(x) / int(x) / bool(x) on a plausible tensor
    if isinstance(node.func, ast.Name) and \
            node.func.id in _BUILTIN_CASTS and len(node.args) == 1 and \
            _plausibly_traced_arg(node.args[0]):
        findings.append(Finding(
            'host-sync', HIGH,
            f'{node.func.id}(...) on a (possibly traced) tensor: '
            'inside a traced step this is a device->host sync per call '
            '(or a TracerConversion error under jit). Keep the value '
            'on device (jnp) and materialize only at log/epoch '
            'boundaries.',
            file=filename, line=line, origin='ast'))
        return
    # np.asarray / np.array
    if isinstance(node.func, ast.Attribute) and \
            fname in _NUMPY_SYNC_FUNCS and \
            isinstance(node.func.value, ast.Name) and \
            node.func.value.id in _NUMPY_MODULES and node.args and \
            _plausibly_traced_arg(node.args[0]):
        findings.append(Finding(
            'host-sync', HIGH,
            f'np.{fname}(...) on a (possibly traced) tensor pulls it '
            'to the host. Use jnp on device, or materialize at '
            'log/epoch boundaries.',
            file=filename, line=line, origin='ast'))
        return
    # x.numpy() / x.item() / x.tolist()
    if isinstance(node.func, ast.Attribute) and \
            fname in _TENSOR_SYNC_METHODS and not node.args:
        findings.append(Finding(
            'host-sync', HIGH,
            f'.{fname}() forces a device->host sync; inside a traced '
            'function it fails under jit. Stay in jnp, or move the '
            'readback to a log boundary.',
            file=filename, line=line, origin='ast'))
        return
    # bare print of (possibly) traced values
    if isinstance(node.func, ast.Name) and node.func.id == 'print' \
            and any(_plausibly_traced_arg(a) for a in node.args):
        findings.append(Finding(
            'host-sync', INFO,
            'print() in traced code runs at trace time only (and '
            'syncs if it formats device values). Use '
            'jax.debug.print for runtime prints.',
            file=filename, line=line, origin='ast'))


def _loop_spans(fn):
    """(start, end) line spans of every for/while body inside `fn`."""
    spans = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            spans.append((node.lineno,
                          getattr(node, 'end_lineno', node.lineno)))
    return spans


def _demote_host_finding(f, in_loop):
    """Host-audit demotion: per-iteration syncs in host loops are WARN
    (the step-loop hazard the sweep hunts), boundary syncs INFO."""
    if f.severity != HIGH:
        return f
    if in_loop:
        f.severity = WARN
        f.message += (' [host-scope: per-iteration sync in a host '
                      'loop — intentional for timing/readback, move '
                      'to boundaries otherwise]')
    else:
        f.severity = INFO
        f.message += (' [host-scope: outside any loop — boundary '
                      'materialization is normal host code]')
    return f


def lint_source(src, filename='<source>', scope='traced', disable=(),
                apply_suppress=True, host_audit=False):
    """Lint python source text; returns a list of Findings.

    scope='traced': only functions the framework will trace (see
    module docstring).  scope='all': every function — audit mode for
    host-side step loops.  host_audit=True (what lint_file sets for
    scope='all') additionally demotes findings in NON-traced
    functions: WARN inside for/while bodies, INFO outside (see module
    docstring).  apply_suppress=False skips the in-pass suppression
    check — for callers whose line numbers are RELATIVE to a snippet
    (lint_callable) and must re-anchor before checking comments
    against the real file."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding('parse-error', INFO,
                        f'could not parse: {e}', file=filename,
                        line=getattr(e, 'lineno', None), origin='ast')]
    traced = _Scoper(tree).traced
    if scope == 'all':
        targets = [n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        if not targets:
            targets = [tree]        # lint module-level statements too
        else:
            # traced defs first: a traced fn nested inside a host fn
            # must claim its calls at full severity before the host
            # walk (which would demote them) reaches them
            targets.sort(key=lambda n: (n not in traced, n.lineno))
    else:
        targets = sorted(traced, key=lambda n: n.lineno)

    findings = []
    seen = set()
    spans = _def_spans(tree)
    for fn in targets:
        demote = host_audit and scope == 'all' and fn not in traced
        loops = _loop_spans(fn) if demote else ()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and id(node) not in seen:
                seen.add(id(node))
                before = len(findings)
                _check_call(node, findings, filename)
                if demote:
                    for f in findings[before:]:
                        _demote_host_finding(f, any(
                            s <= (f.line or 0) <= e for s, e in loops))
                if not apply_suppress:
                    continue
                # line-level + enclosing-def-level suppression (every
                # def whose span contains the finding — nested defs
                # included), checked against the real file
                for f in findings[before:]:
                    if _is_suppressed(
                            f.rule, filename, f.line,
                            _enclosing_def_lines(spans, f.line)):
                        findings.remove(f)
    return findings


def lint_file(path, scope='traced', disable=()):
    with open(path, 'r', encoding='utf-8') as fh:
        src = fh.read()
    linecache.checkcache(path)
    return lint_source(src, filename=path, scope=scope, disable=disable,
                       host_audit=(scope == 'all'))


def lint_callable(fn, scope='traced', disable=()):
    """AST-lint a live callable's source (best effort: decorated or
    dynamically-generated functions without retrievable source yield
    no findings)."""
    import inspect
    import textwrap
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        path = inspect.getsourcefile(fn)
        _, base_line = inspect.getsourcelines(fn)
    except (OSError, TypeError):
        return []
    # suppressions are deferred (apply_suppress=False): the snippet's
    # line numbers are relative, so checking comments against the real
    # file before re-anchoring would read the WRONG lines
    findings = lint_source(src, filename=path or '<source>',
                           scope='all', disable=disable,
                           apply_suppress=False)
    # re-anchor lines (and the def spans used for function-level
    # suppression) to the real file; base_line points at the first
    # snippet line — a decorator when present — while _def_spans
    # reports the actual `def` lines
    try:
        spans = [(s + base_line - 1, e + base_line - 1)
                 for s, e in _def_spans(ast.parse(src))]
    except SyntaxError:       # pragma: no cover - parsed above already
        spans = []
    for f in findings:
        if f.line is not None:
            f.line = f.line + base_line - 1
    return [f for f in findings
            if not _is_suppressed(
                f.rule, f.file, f.line,
                _enclosing_def_lines(spans, f.line))]
