"""paddle_tpu.analysis — jaxpr-level TPU lint (static analysis).

The paper's premise is that paddle_tpu programs compile cleanly to
XLA; nothing in a passing test suite proves a model *stays* compiled —
silent retraces, per-step host syncs, replicated giants and f32 creep
all degrade to "slow but correct".  This subsystem checks those
properties statically:

* a **jaxpr walker** (``walker``) traces the exact program XLA will
  compile (jax.make_jaxpr — no device execution) and a rule registry
  (``rules``) audits it: ``recompile-hazard``, ``host-sync``,
  ``replicated-giant``, ``amp-promotion``, ``donation-violation``,
  ``constant-capture``;
* an **AST pre-trace linter** (``ast_lint``) sweeps dy2static sources
  for host syncs the tracer would hit before a jaxpr exists;
* **runtime companions** (``runtime``): an eager dtype audit riding
  core/dispatch, and the retrace monitor compile caches report into;
* an **auto-sharding planner** (``planner``): enumerates candidate
  mesh shapes and PartitionSpec assignments for a step function,
  scores each through the lowered-HLO audit (torus-decomposed
  collective cost via ``costmodel`` + liveness peak memory vs an HBM
  budget) and returns ranked plans — ``tpu_lint --plan`` and
  ``ParallelTrainer(auto_shard=True)``.

Entry points:

    report = analysis.lint(step_fn, *example_args,
                           mesh=mesh, donate_argnums=(0, 2))
    report = analysis.lint_sources(['examples/', 'paddle_tpu/models/'])

Wired in at every compile choke point: ``jit.to_static(check=...)``,
``static.Program.lint()`` / ``Executor.run(check=...)``,
``hapi.Model.prepare(lint=...)``, ``ParallelTrainer(lint=...)``, and
the ``tools/tpu_lint.py`` CLI (the tier-1 self-lint gate).

Suppression: ``# tpu-lint: disable=rule-id`` on the flagged line (or
the enclosing ``def``), or ``disable=('rule-id',)`` on any entry
point.
"""
import functools
import os
import warnings

import jax
import jax.numpy as jnp

from .findings import (  # noqa: F401
    Finding, LintReport, LintError, LintWarning, HIGH, WARN, INFO,
    SEVERITIES)
from . import walker  # noqa: F401
from . import rules as _rules_mod
from .rules import (  # noqa: F401
    RULES, register_rule, RuleContext, DEFAULT_THRESHOLDS, run_rules,
    scalar_arg_findings)
from . import ast_lint  # noqa: F401
from .ast_lint import (  # noqa: F401
    lint_source, lint_file, lint_callable, apply_suppressions)
from .runtime import amp_audit, note_retrace, OpDtypeAudit  # noqa: F401
from . import costmodel  # noqa: F401
from . import hlo  # noqa: F401
from .hlo import (  # noqa: F401
    HLO_RULES, register_hlo_rule, DEFAULT_HLO_THRESHOLDS)
from . import targets  # noqa: F401
from . import planner  # noqa: F401
from .planner import plan_model  # noqa: F401
from . import threads  # noqa: F401
from .threads import (  # noqa: F401
    lint_threads_source, lint_threads_file, lint_threads_sources,
    THREAD_RULES, register_thread_rule)
from . import lockcheck  # noqa: F401
from .lockcheck import (  # noqa: F401
    LockChecker, resolve_lockcheck)
# importing spmd also registers its HLO collective-order rule into
# HLO_RULES, so every --hlo audit checks conditional branch parity
from . import spmd  # noqa: F401
from .spmd import (  # noqa: F401
    lint_spmd_source, lint_spmd_file, lint_spmd_sources,
    SPMD_RULES, register_spmd_rule)

# the lowered-HLO SPMD audit (post-partitioner: sharding placement,
# collective cost, per-device peak memory) — the escalation the
# compile choke points run when a Mesh is active
lint_hlo = hlo.audit


def escalate_hlo(report, fn, state_args, batch_args, mesh, *,
                 donate_argnums=(), name=None):
    """The shared choke-point posture for the mesh-gated HLO
    escalation: `state_args` replicated, `batch_args` sharded on the
    mesh's data axis when divisible (hlo.auto_shardings heuristic,
    replicated fallback), findings extend `report` in place.
    ParallelTrainer does NOT use this — it lowers with its real jit
    shardings and donation instead."""
    from jax.sharding import NamedSharding, PartitionSpec
    repl = NamedSharding(mesh, PartitionSpec())
    rep_tree = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda _: repl, t)
    batch_sh = tuple(hlo.auto_shardings(mesh, tuple(batch_args)) or
                     (rep_tree(b) for b in batch_args))
    in_sh = tuple(rep_tree(a) for a in state_args) + batch_sh
    return report.extend(lint_hlo(
        fn, *state_args, *batch_args, mesh=mesh, in_shardings=in_sh,
        donate_argnums=donate_argnums,
        global_shapes=getattr(report, 'global_big_shapes', None),
        name=name))


__all__ = ['lint', 'lint_sources', 'lint_layer', 'lint_hlo',
           'escalate_hlo', 'emit',
           'safe_emit',
           'Finding', 'LintReport', 'LintError', 'LintWarning',
           'HIGH', 'WARN', 'INFO', 'RULES', 'register_rule',
           'RuleContext', 'run_rules', 'DEFAULT_THRESHOLDS',
           'scalar_arg_findings', 'HLO_RULES', 'register_hlo_rule',
           'DEFAULT_HLO_THRESHOLDS',
           'lint_source', 'lint_file', 'lint_callable',
           'apply_suppressions', 'amp_audit', 'note_retrace',
           'walker', 'ast_lint', 'hlo', 'costmodel', 'targets',
           'planner', 'plan_model',
           'threads', 'lint_threads_source', 'lint_threads_file',
           'lint_threads_sources', 'THREAD_RULES',
           'register_thread_rule', 'lockcheck', 'LockChecker',
           'resolve_lockcheck',
           'spmd', 'lint_spmd_source', 'lint_spmd_file',
           'lint_spmd_sources', 'SPMD_RULES', 'register_spmd_rule']


def _leaf_ranges(example_args):
    """Flat-invar index range each positional arg occupies."""
    ranges = []
    start = 0
    for a in example_args:
        n = len(jax.tree_util.tree_leaves(a))
        ranges.append((start, start + n))
        start += n
    return ranges


def lint(fn, *example_args, mesh=None, donate_argnums=(), disable=(),
         signatures=None, thresholds=None, name=None, source=True,
         fused_steps=None, **example_kwargs):
    """Trace `fn` abstractly and run every registered jaxpr rule.

    example_args: concrete arrays / pytrees / jax.ShapeDtypeStruct
    placeholders — Python scalars are recorded as recompile hazards
    and traced as arrays so the walk still completes.
    mesh: active jax.sharding.Mesh (enables replicated-giant).
    donate_argnums: positions the real jit call donates (enables
    donation-violation).
    signatures: optional list of per-call shape tuples the step has
    already seen (enables the shape-variance hazard).
    source: additionally AST-lint `fn`'s own source when retrievable.

    Returns a LintReport; raises nothing — gate with
    report.raise_for('high') or analysis.emit(report, 'error').
    """
    name = name or getattr(fn, '__name__', None) or 'step'
    python_scalars = []
    traced_args = []
    for i, a in enumerate(example_args):
        if isinstance(a, (bool, int, float)):
            python_scalars.append((i, a))
            traced_args.append(jnp.asarray(a))
        else:
            traced_args.append(a)
    findings = []
    closed = None
    try:
        closed = walker.trace_jaxpr(fn, *traced_args, **example_kwargs)
    except (jax.errors.TracerBoolConversionError,
            jax.errors.ConcretizationTypeError,
            jax.errors.TracerArrayConversionError) as e:
        # the trace itself hit a host materialization — that IS the
        # host-sync finding, with jax's own diagnosis attached
        first = str(e).strip().split('\n')[0]
        findings.append(Finding(
            'host-sync', HIGH,
            f'tracing {name} aborted on a host materialization of a '
            f'traced value: {first}',
            origin='jaxpr'))
    if closed is not None:
        ctx = RuleContext(
            closed, mesh=mesh, donate_argnums=donate_argnums,
            arg_leaf_ranges=_leaf_ranges(traced_args),
            python_scalars=python_scalars, signatures=signatures,
            thresholds=thresholds, name=name, fused_steps=fused_steps)
        findings.extend(run_rules(ctx, disable=disable))
    if source:
        findings.extend(lint_callable(fn, disable=disable))
    findings = [f for f in apply_suppressions(findings)
                if f.rule not in disable]
    report = LintReport(findings, name=name)
    if closed is not None:
        # thunk, NOT extras: a set of tuples is side data for the HLO
        # escalation (lint_hlo(global_shapes=...) skips its second
        # abstract trace), and only the mesh-gated escalation reads it
        # — the common single-device path never pays the extra walk
        thr = (thresholds or {}).get(
            'replicated_bytes',
            DEFAULT_HLO_THRESHOLDS['replicated_bytes'])
        report._big_shapes_thunk = functools.partial(
            hlo.global_big_shapes_of, closed, thr)
    return report


def _iter_py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.endswith('.py'):
                        yield os.path.join(root, f)
        elif p.endswith('.py'):
            yield p


def lint_sources(paths, scope='traced', disable=()):
    """AST-lint .py files / directories (no imports, no execution).
    This is what tools/tpu_lint.py and the tier-1 self-lint gate
    run over examples/ and paddle_tpu/models/."""
    findings = []
    for path in _iter_py_files(paths):
        findings.extend(lint_file(path, scope=scope, disable=disable))
    findings = [f for f in findings if f.rule not in disable]
    return LintReport(findings, name='sources')


def lint_layer(layer, disable=()):
    """AST-lint a Layer's forward (and its direct sublayers' forwards)
    — the pre-trace half of Model.prepare(lint=...)."""
    seen, findings = set(), []

    def one(lyr):
        cls = type(lyr)
        if cls in seen:
            return
        seen.add(cls)
        fwd = getattr(cls, 'forward', None)
        if fwd is not None and 'paddle_tpu/nn/' not in (
                getattr(fwd, '__code__', None) and
                fwd.__code__.co_filename or ''):
            findings.extend(lint_callable(fwd, disable=disable))

    one(layer)
    for _name, sub in getattr(layer, 'named_sublayers', lambda: [])():
        one(sub)
    findings = [f for f in findings if f.rule not in disable]
    return LintReport(findings, name=type(layer).__name__)


def emit(report, mode='warn'):
    """Standard surfacing for the compile-choke-point integrations.

    mode: falsy -> silent; 'warn'/True -> one LintWarning per report;
    'error' -> LintError on any high-severity finding (lower ones
    still warn).  Findings additionally land as telemetry
    ``lint_finding`` events (countable per run, and part of the bench
    artifact's evidence chain) regardless of warn/error mode."""
    if not mode or not report:
        return report
    _telemetry_findings(report)
    if mode == 'error' and report.high:
        raise LintError(report.render(report.high), report=report)
    warnings.warn(str(report), LintWarning, stacklevel=3)
    return report


def _telemetry_findings(report):
    """One ``lint_finding`` telemetry event per finding (never
    raises — telemetry must not break a compile)."""
    try:
        from .. import telemetry
        for f in report:
            telemetry.event('lint_finding', rule=f.rule,
                            severity=f.severity, file=f.file,
                            line=f.line, origin=f.origin,
                            name=report.name)
            telemetry.add(f'lint.{f.severity}')
    except Exception:       # pragma: no cover - defensive
        pass


def safe_emit(build_report, mode):
    """emit() under the integration contract shared by every compile
    choke point (to_static / Model.prepare / ParallelTrainer /
    Executor): `build_report` (a zero-arg callable returning a
    LintReport) plus emit() run guarded — only LintError, the
    'error'-mode verdict, escapes; an analyzer crash degrades to a
    LintWarning instead of breaking the user's compile."""
    if not mode:
        return None
    try:
        return emit(build_report(), mode)
    except LintError:
        raise
    except Exception as e:        # pragma: no cover - analyzer bug
        warnings.warn(f'tpu-lint skipped ({e!r})', LintWarning,
                      stacklevel=3)
        return None
